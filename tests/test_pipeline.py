"""Pipeline parallelism (pp axis): GPipe schedule vs serial reference."""

import jax
import jax.numpy as jnp
import numpy as np

from tf_operator_tpu.compat import shard_map
from tf_operator_tpu.parallel.mesh import MeshConfig, make_mesh
from tf_operator_tpu.parallel.pipeline import (
    merge_microbatches,
    pipeline_sharded,
    split_microbatches,
    stack_stage_params,
)

HID = 16


def stage_fn(params, x):
    # residual MLP stage: x + gelu(x @ w1) @ w2
    return x + jax.nn.gelu(x @ params["w1"]) @ params["w2"]


def make_params(n_stages, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), n_stages * 2)
    return [
        {"w1": jax.random.normal(ks[2 * i], (HID, 4 * HID)) * 0.1,
         "w2": jax.random.normal(ks[2 * i + 1], (4 * HID, HID)) * 0.1}
        for i in range(n_stages)
    ]


def serial_apply(per_stage, x):
    for p in per_stage:
        x = stage_fn(p, x)
    return x


def test_split_merge_roundtrip():
    x = jnp.arange(24.0).reshape(8, 3)
    mb = split_microbatches(x, 4)
    assert mb.shape == (4, 2, 3)
    np.testing.assert_array_equal(merge_microbatches(mb), x)


def test_pipeline_matches_serial():
    mesh = make_mesh(MeshConfig(dp=2, pp=4))
    per_stage = make_params(4)
    stacked = stack_stage_params(per_stage)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, HID))
    ref = serial_apply(per_stage, x)
    out = pipeline_sharded(stage_fn, stacked, x, mesh, num_microbatches=4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_pipeline_gradients_match_serial():
    mesh = make_mesh(MeshConfig(dp=1, pp=8))
    per_stage = make_params(8, seed=2)
    stacked = stack_stage_params(per_stage)
    x = jax.random.normal(jax.random.PRNGKey(3), (16, HID))

    def loss_pipe(stacked):
        y = pipeline_sharded(stage_fn, stacked, x, mesh,
                             num_microbatches=8)
        return jnp.mean(y ** 2)

    def loss_serial(stacked):
        per = [jax.tree_util.tree_map(lambda p: p[i], stacked)
               for i in range(8)]
        return jnp.mean(serial_apply(per, x) ** 2)

    g_pipe = jax.grad(loss_pipe)(stacked)
    g_ser = jax.grad(loss_serial)(stacked)
    for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                    jax.tree_util.tree_leaves(g_ser)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-4)


def test_pipeline_under_jit_with_dp():
    # jit the whole thing over a dp×pp mesh: the usual training shape.
    mesh = make_mesh(MeshConfig(dp=2, pp=4))
    per_stage = make_params(4, seed=4)
    stacked = stack_stage_params(per_stage)
    x = jax.random.normal(jax.random.PRNGKey(5), (16, HID))

    @jax.jit
    def fwd(stacked, x):
        return pipeline_sharded(stage_fn, stacked, x, mesh,
                                num_microbatches=4)

    ref = serial_apply(per_stage, x)
    np.testing.assert_allclose(np.asarray(fwd(stacked, x)),
                               np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_bad_microbatch_split_raises():
    import pytest

    with pytest.raises(ValueError):
        split_microbatches(jnp.zeros((6, 4)), 4)


class Test1F1B:
    """1F1B fused schedule vs serial autodiff reference."""

    def _serial_loss(self, stacked, x, targets, n_stages, loss_fn):
        per = [jax.tree_util.tree_map(lambda p: p[i], stacked)
               for i in range(n_stages)]
        return loss_fn(serial_apply(per, x), targets)

    def test_loss_and_grads_match_serial(self):
        from tf_operator_tpu.parallel.pipeline import pipeline_train_sharded

        mesh = make_mesh(MeshConfig(dp=1, pp=8))
        per_stage = make_params(8, seed=7)
        stacked = stack_stage_params(per_stage)
        x = jax.random.normal(jax.random.PRNGKey(8), (8, HID))
        targets = jax.random.normal(jax.random.PRNGKey(9), (8, HID))

        def loss_fn(y, t):
            return jnp.mean((y - t) ** 2)

        loss, grads = pipeline_train_sharded(
            stage_fn, loss_fn, stacked, x, targets, mesh,
            num_microbatches=4)

        # Serial reference: mean over microbatches of per-mb mean loss
        # (= global mean here since microbatches are equal-sized).
        ref_loss, ref_grads = jax.value_and_grad(
            lambda p: self._serial_loss(p, x, targets, 8, loss_fn))(stacked)
        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   atol=1e-5, rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(grads),
                        jax.tree_util.tree_leaves(ref_grads)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5, rtol=1e-4)

    def test_with_data_parallel_axis(self):
        from tf_operator_tpu.parallel.pipeline import pipeline_train_sharded

        mesh = make_mesh(MeshConfig(dp=2, pp=4))
        per_stage = make_params(4, seed=10)
        stacked = stack_stage_params(per_stage)
        x = jax.random.normal(jax.random.PRNGKey(11), (16, HID))
        targets = jax.random.normal(jax.random.PRNGKey(12), (16, HID))

        def loss_fn(y, t):
            return jnp.mean((y - t) ** 2)

        @jax.jit
        def train(p, x, t):
            return pipeline_train_sharded(stage_fn, loss_fn, p, x, t,
                                          mesh, num_microbatches=4)

        loss, grads = train(stacked, x, targets)
        ref_loss, ref_grads = jax.value_and_grad(
            lambda p: self._serial_loss(p, x, targets, 4, loss_fn))(stacked)
        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   atol=1e-5, rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(grads),
                        jax.tree_util.tree_leaves(ref_grads)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5, rtol=1e-4)

    def test_single_stage_degenerates_cleanly(self):
        from tf_operator_tpu.parallel.pipeline import pipeline_train_sharded

        mesh = make_mesh(MeshConfig(dp=8, pp=1))
        stacked = stack_stage_params(make_params(1, seed=13))
        x = jax.random.normal(jax.random.PRNGKey(14), (16, HID))
        targets = jnp.zeros_like(x)

        def loss_fn(y, t):
            return jnp.mean((y - t) ** 2)

        loss, grads = pipeline_train_sharded(stage_fn, loss_fn, stacked,
                                             x, targets, mesh,
                                             num_microbatches=2)
        ref_loss, ref_grads = jax.value_and_grad(
            lambda p: self._serial_loss(p, x, targets, 1, loss_fn))(stacked)
        np.testing.assert_allclose(float(loss), float(ref_loss),
                                   atol=1e-5, rtol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(grads),
                        jax.tree_util.tree_leaves(ref_grads)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5, rtol=1e-4)


def test_last_stage_only_output():
    from tf_operator_tpu.parallel.pipeline import (
        pipeline_apply,
    )
    from jax.sharding import PartitionSpec as P

    mesh = make_mesh(MeshConfig(dp=2, pp=4))
    per_stage = make_params(4, seed=15)
    stacked = stack_stage_params(per_stage)
    x = jax.random.normal(jax.random.PRNGKey(16), (8, HID))
    mb = split_microbatches(x, 4)

    def inner(params, mbx):
        local = jax.tree_util.tree_map(lambda p: p[0], params)
        return pipeline_apply(stage_fn, local, mbx, gather_output=False)

    pspec = jax.tree_util.tree_map(lambda _: P("pp"), stacked)
    # With gather_output=False ranks disagree (zeros off the last
    # stage), so out_specs=P() replication would be wrong — fetch
    # per-rank outputs via a pp-leading axis instead.
    fn = shard_map(
        lambda p, mbx: inner(p, mbx)[None], mesh=mesh,
        in_specs=(pspec, P()), out_specs=P("pp"), check_vma=False)
    per_rank = fn(stacked, mb)
    ref = serial_apply(per_stage, x)
    # Last rank carries the real outputs; earlier ranks carry zeros.
    np.testing.assert_allclose(
        np.asarray(merge_microbatches(per_rank[-1])), np.asarray(ref),
        atol=1e-5, rtol=1e-5)
    assert float(jnp.abs(per_rank[:-1]).max()) == 0.0


def test_1f1b_log_loss_no_nan_from_bubble_ticks():
    """Bubble ticks backward garbage (zeroed ring slots); with a loss
    whose gradient explodes on zeros (log), masking must SELECT the
    gradient away, not multiply NaN by zero."""
    from tf_operator_tpu.parallel.pipeline import pipeline_train_sharded

    mesh = make_mesh(MeshConfig(dp=2, pp=4))
    per_stage = make_params(4, seed=21)
    stacked = stack_stage_params(per_stage)
    # Keep activations positive so log() is finite on REAL data but
    # -inf/NaN on the zero-initialized bubble residuals.
    x = jnp.abs(jax.random.normal(jax.random.PRNGKey(22), (16, HID))) + 0.5
    targets = jnp.zeros_like(x)

    def loss_fn(y, t):
        return jnp.mean(jnp.log(y ** 2 + 1e-6))

    loss, grads = pipeline_train_sharded(stage_fn, loss_fn, stacked, x,
                                         targets, mesh, num_microbatches=4)
    assert bool(jnp.isfinite(loss)), float(loss)
    for leaf in jax.tree_util.tree_leaves(grads):
        assert bool(jnp.all(jnp.isfinite(leaf)))


def test_pipeline_lm_full_model_grads_match_serial():
    """Full LM through 1F1B: embedding -> pp trunk -> untied head, all
    three gradient groups exact vs serial autodiff."""
    from tf_operator_tpu.parallel.pipeline import pipeline_lm_train_sharded

    V, PP = 32, 4
    mesh = make_mesh(MeshConfig(dp=2, pp=PP))
    per_stage = make_params(PP, seed=31)
    stacked = stack_stage_params(per_stage)
    rng = jax.random.PRNGKey(32)
    embed = {"table": jax.random.normal(rng, (V, HID)) * 0.5}
    head = {"w": jax.random.normal(jax.random.fold_in(rng, 1),
                                   (HID, V)) * 0.5}
    tokens = jax.random.randint(jax.random.fold_in(rng, 2), (16,), 0, V)
    labels = jax.random.randint(jax.random.fold_in(rng, 3), (16,), 0, V)

    def embed_fn(ep, tok):
        return ep["table"][tok]          # [m, mb] -> [m, mb, HID]

    def loss_fn(y, t, hp):
        logits = y @ hp["w"]
        logp = jax.nn.log_softmax(logits)
        return -jnp.take_along_axis(logp, t[..., None], axis=-1).mean()

    loss, sgrads, egrads, hgrads = pipeline_lm_train_sharded(
        stage_fn, loss_fn, embed_fn, stacked, embed, head,
        tokens, labels, mesh, num_microbatches=4)

    def serial(stacked, embed, head):
        x = embed["table"][tokens]
        for i in range(PP):
            x = stage_fn(jax.tree_util.tree_map(lambda p: p[i], stacked), x)
        logits = x @ head["w"]
        logp = jax.nn.log_softmax(logits)
        return -jnp.take_along_axis(logp, labels[..., None],
                                    axis=-1).mean()

    ref_loss, (ref_s, ref_e, ref_h) = jax.value_and_grad(
        serial, argnums=(0, 1, 2))(stacked, embed, head)
    np.testing.assert_allclose(float(loss), float(ref_loss),
                               atol=1e-5, rtol=1e-5)
    for got, want, tag in ((sgrads, ref_s, "stage"), (egrads, ref_e,
                                                      "embed"),
                           (hgrads, ref_h, "head")):
        for a, b in zip(jax.tree_util.tree_leaves(got),
                        jax.tree_util.tree_leaves(want)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5, rtol=1e-4,
                                       err_msg=tag)


def test_llama_pipeline_matches_serial_model():
    """The REAL Llama decoder through the 1F1B pipeline: loss and every
    parameter group's gradient match the plain (non-pp) model."""
    import dataclasses

    from tf_operator_tpu.models.llama import Llama, llama_tiny
    from tf_operator_tpu.parallel.llama_pp import (
        init_llama_params,
        llama_pp_loss_and_grads,
    )

    cfg = dataclasses.replace(
        llama_tiny(vocab_size=64, max_seq_len=32), n_layers=4,
        dtype=jnp.float32, attention_impl="xla")
    mesh = make_mesh(MeshConfig(dp=2, pp=4))
    rng = jax.random.PRNGKey(41)
    tokens = jax.random.randint(jax.random.fold_in(rng, 1), (8, 17), 0,
                                cfg.vocab_size)
    params = init_llama_params(cfg, rng, tokens[:, :-1])

    loss, grads = llama_pp_loss_and_grads(cfg, params, tokens, mesh,
                                          num_microbatches=4)

    def serial_loss(params):
        logits = Llama(cfg).apply({"params": params}, tokens[:, :-1])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        return -jnp.take_along_axis(
            logp, tokens[:, 1:][..., None], axis=-1).mean()

    ref_loss, ref_grads = jax.value_and_grad(serial_loss)(params)
    np.testing.assert_allclose(float(loss), float(ref_loss),
                               atol=1e-5, rtol=1e-5)
    flat_got = dict(jax.tree_util.tree_leaves_with_path(grads))
    flat_want = dict(jax.tree_util.tree_leaves_with_path(ref_grads))
    assert flat_got.keys() == flat_want.keys()
    for path in flat_want:
        np.testing.assert_allclose(
            np.asarray(flat_got[path]), np.asarray(flat_want[path]),
            atol=2e-5, rtol=2e-4, err_msg=str(path))


def test_llama_pipeline_trainer_trains():
    """LlamaPipelineTrainer: placement (blocks pp-sharded, embed/head
    replicated), jitted step, loss decreases on a fixed batch."""
    import dataclasses

    import optax
    from jax.sharding import PartitionSpec as P

    from tf_operator_tpu.models.llama import llama_tiny
    from tf_operator_tpu.parallel.llama_pp import LlamaPipelineTrainer

    cfg = dataclasses.replace(
        llama_tiny(vocab_size=64, max_seq_len=32), n_layers=4,
        dtype=jnp.float32, attention_impl="xla")
    mesh = make_mesh(MeshConfig(dp=2, pp=4))
    trainer = LlamaPipelineTrainer(cfg, mesh, optax.adam(3e-3),
                                   num_microbatches=4)
    rng = jax.random.PRNGKey(51)
    tokens = jax.random.randint(jax.random.fold_in(rng, 1), (8, 17), 0,
                                cfg.vocab_size)
    state, shardings = trainer.init(rng, tokens[:, :-1])

    # Stage stacks actually sharded over pp; embed replicated.
    wq = state.params["blocks"]["attn"]["wq"]["kernel"]
    assert wq.sharding.spec == P("pp")
    assert state.params["embed_tokens"]["embedding"].sharding.spec == P()
    mu_wq = state.opt_state[0].mu["blocks"]["attn"]["wq"]["kernel"]
    assert mu_wq.sharding.spec == P("pp")

    step = trainer.make_train_step(shardings)
    losses = []
    for _ in range(8):
        state, metrics = step(state, tokens)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] - 0.5, losses
    assert int(state.step) == 8


def test_llama_pipeline_trainer_checkpoint_resume(tmp_path):
    """The pp-sharded trainer state round-trips through orbax and
    resumes identically — restart policies work for pipeline training."""
    import dataclasses

    import optax

    from tf_operator_tpu.models.llama import llama_tiny
    from tf_operator_tpu.parallel.llama_pp import LlamaPipelineTrainer
    from tf_operator_tpu.train.checkpoint import Checkpointer

    cfg = dataclasses.replace(
        llama_tiny(vocab_size=64, max_seq_len=32), n_layers=4,
        dtype=jnp.float32, attention_impl="xla")
    mesh = make_mesh(MeshConfig(dp=2, pp=4))
    trainer = LlamaPipelineTrainer(cfg, mesh, optax.adam(3e-3),
                                   num_microbatches=4)
    rng = jax.random.PRNGKey(61)
    tokens = jax.random.randint(jax.random.fold_in(rng, 1), (8, 17), 0,
                                cfg.vocab_size)
    state, shardings = trainer.init(rng, tokens[:, :-1])
    step = trainer.make_train_step(shardings)
    for _ in range(3):
        state, m = step(state, tokens)

    ckpt = Checkpointer(str(tmp_path / "ckpt"))
    assert ckpt.save(int(state.step), state)
    ckpt.wait()

    trainer2 = LlamaPipelineTrainer(cfg, mesh, optax.adam(3e-3),
                                    num_microbatches=4)
    # Restore target from shapes alone — no throwaway init.
    sh2 = trainer2.state_shardings(jax.random.PRNGKey(62),
                                   tokens[:, :-1])
    restored = ckpt.restore(trainer2.abstract_state(
        jax.random.PRNGKey(62), tokens[:, :-1], shardings=sh2))
    assert int(restored.step) == 3
    # Restored stage stacks keep their pp sharding.
    from jax.sharding import PartitionSpec as P
    wq = restored.params["blocks"]["attn"]["wq"]["kernel"]
    assert wq.sharding.spec == P("pp")

    # Optimizer moments round-trip exactly (compare BEFORE stepping:
    # the donating step invalidates its input buffers).
    for a, b in zip(jax.tree_util.tree_leaves(state.opt_state),
                    jax.tree_util.tree_leaves(restored.opt_state),
                    strict=True):
        np.testing.assert_array_equal(np.asarray(jax.device_get(a)),
                                      np.asarray(jax.device_get(b)))

    # Two chained steps on each side stay identical — a corrupt
    # restored moment would diverge by the second step.
    step2 = trainer2.make_train_step(sh2)
    state_a, ma = step(state, tokens)
    state_b, mb = step2(restored, tokens)
    assert abs(float(ma["loss"]) - float(mb["loss"])) < 1e-5
    _, ma2 = step(state_a, tokens)
    _, mb2 = step2(state_b, tokens)
    assert abs(float(ma2["loss"]) - float(mb2["loss"])) < 1e-5
    ckpt.close()


# ---------------------------------------------------------------------------
# Round-4: GPipe full-LM composition + schedule auto-selection
# ---------------------------------------------------------------------------

def test_pipeline_lm_gpipe_matches_1f1b_and_serial():
    """The GPipe full-LM path computes the SAME loss and gradients as
    the 1F1B path and serial autodiff — schedules are pure execution
    strategies, never semantics."""
    from tf_operator_tpu.parallel.pipeline import (
        pipeline_lm_train_gpipe,
        pipeline_lm_train_sharded,
    )

    V, PP = 32, 4
    mesh = make_mesh(MeshConfig(dp=2, pp=PP))
    stacked = stack_stage_params(make_params(PP, seed=41))
    rng = jax.random.PRNGKey(42)
    embed = {"table": jax.random.normal(rng, (V, HID)) * 0.5}
    head = {"w": jax.random.normal(jax.random.fold_in(rng, 1),
                                   (HID, V)) * 0.5}
    tokens = jax.random.randint(jax.random.fold_in(rng, 2), (16,), 0, V)
    labels = jax.random.randint(jax.random.fold_in(rng, 3), (16,), 0, V)

    def embed_fn(ep, tok):
        return ep["table"][tok]

    def loss_fn(y, t, hp):
        logits = y @ hp["w"]
        logp = jax.nn.log_softmax(logits)
        return -jnp.take_along_axis(logp, t[..., None], axis=-1).mean()

    args = (stage_fn, loss_fn, embed_fn, stacked, embed, head,
            tokens, labels, mesh)
    l_g, s_g, e_g, h_g = pipeline_lm_train_gpipe(*args,
                                                 num_microbatches=4)
    l_f, s_f, e_f, h_f = pipeline_lm_train_sharded(*args,
                                                   num_microbatches=4)
    np.testing.assert_allclose(float(l_g), float(l_f), atol=1e-5,
                               rtol=1e-5)
    for got, want in ((s_g, s_f), (e_g, e_f), (h_g, h_f)):
        for a, b in zip(jax.tree_util.tree_leaves(got),
                        jax.tree_util.tree_leaves(want), strict=True):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)


def test_select_schedule_policy():
    from tf_operator_tpu.parallel.pipeline import select_schedule

    assert select_schedule(10**6, None) == "gpipe"      # unbounded budget
    assert select_schedule(10**6, 10**9) == "gpipe"     # fits
    assert select_schedule(10**9, 10**6) == "1f1b"      # memory-bound
    # The safety margin: just-barely-at-budget is NOT a fit.
    assert select_schedule(10**6, 10**6) == "1f1b"
    # Fail SAFE: a real budget with an unknown footprint must not
    # gamble on the memory-hungry schedule.
    assert select_schedule(None, 10**9) == "1f1b"
    assert select_schedule(None, None) == "gpipe"


def test_llama_pipeline_trainer_schedule_auto_and_forced():
    """Auto keeps GPipe under an ample budget and falls back to 1F1B
    under a tight one; both schedules train the same model, and the
    choice is observable (resolved_schedule)."""
    import dataclasses

    import optax

    from tf_operator_tpu.models.llama import llama_tiny
    from tf_operator_tpu.parallel.llama_pp import LlamaPipelineTrainer

    cfg = dataclasses.replace(
        llama_tiny(vocab_size=64, max_seq_len=32), n_layers=4,
        dtype=jnp.float32, attention_impl="xla")
    mesh = make_mesh(MeshConfig(dp=2, pp=4))
    rng = jax.random.PRNGKey(71)
    tokens = jax.random.randint(jax.random.fold_in(rng, 1), (8, 17), 0,
                                cfg.vocab_size)

    # Ample budget -> GPipe (the measured-faster schedule).
    tr = LlamaPipelineTrainer(cfg, mesh, optax.adam(3e-3),
                              num_microbatches=4,
                              memory_budget_bytes=1 << 40)
    state, sh = tr.init(rng, tokens[:, :-1])
    step = tr.make_train_step(sh, sample_tokens=tokens)
    assert tr.resolved_schedule == "gpipe"
    losses = []
    for _ in range(6):
        state, m = step(state, tokens)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], losses

    # Tight budget -> 1F1B (the O(pp)-memory escape hatch).
    tr2 = LlamaPipelineTrainer(cfg, mesh, optax.adam(3e-3),
                               num_microbatches=4,
                               memory_budget_bytes=1)
    state2, sh2 = tr2.init(jax.random.PRNGKey(72), tokens[:, :-1])
    step2 = tr2.make_train_step(sh2, sample_tokens=tokens)
    assert tr2.resolved_schedule == "1f1b"
    state2, m2 = step2(state2, tokens)
    assert np.isfinite(float(m2["loss"]))

    # Forced schedules are respected verbatim.
    tr3 = LlamaPipelineTrainer(cfg, mesh, optax.adam(3e-3),
                               num_microbatches=4, schedule="1f1b")
    _, sh3 = tr3.init(jax.random.PRNGKey(73), tokens[:, :-1])
    tr3.make_train_step(sh3)
    assert tr3.resolved_schedule == "1f1b"

# CI shard (pyproject [tool.pytest.ini_options] markers)
import pytest  # noqa: E402
pytestmark = pytest.mark.compute
