"""Pipeline parallelism (pp axis): GPipe schedule vs serial reference."""

import jax
import jax.numpy as jnp
import numpy as np

from tf_operator_tpu.parallel.mesh import MeshConfig, make_mesh
from tf_operator_tpu.parallel.pipeline import (
    merge_microbatches,
    pipeline_sharded,
    split_microbatches,
    stack_stage_params,
)

HID = 16


def stage_fn(params, x):
    # residual MLP stage: x + gelu(x @ w1) @ w2
    return x + jax.nn.gelu(x @ params["w1"]) @ params["w2"]


def make_params(n_stages, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), n_stages * 2)
    return [
        {"w1": jax.random.normal(ks[2 * i], (HID, 4 * HID)) * 0.1,
         "w2": jax.random.normal(ks[2 * i + 1], (4 * HID, HID)) * 0.1}
        for i in range(n_stages)
    ]


def serial_apply(per_stage, x):
    for p in per_stage:
        x = stage_fn(p, x)
    return x


def test_split_merge_roundtrip():
    x = jnp.arange(24.0).reshape(8, 3)
    mb = split_microbatches(x, 4)
    assert mb.shape == (4, 2, 3)
    np.testing.assert_array_equal(merge_microbatches(mb), x)


def test_pipeline_matches_serial():
    mesh = make_mesh(MeshConfig(dp=2, pp=4))
    per_stage = make_params(4)
    stacked = stack_stage_params(per_stage)
    x = jax.random.normal(jax.random.PRNGKey(1), (8, HID))
    ref = serial_apply(per_stage, x)
    out = pipeline_sharded(stage_fn, stacked, x, mesh, num_microbatches=4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_pipeline_gradients_match_serial():
    mesh = make_mesh(MeshConfig(dp=1, pp=8))
    per_stage = make_params(8, seed=2)
    stacked = stack_stage_params(per_stage)
    x = jax.random.normal(jax.random.PRNGKey(3), (16, HID))

    def loss_pipe(stacked):
        y = pipeline_sharded(stage_fn, stacked, x, mesh,
                             num_microbatches=8)
        return jnp.mean(y ** 2)

    def loss_serial(stacked):
        per = [jax.tree_util.tree_map(lambda p: p[i], stacked)
               for i in range(8)]
        return jnp.mean(serial_apply(per, x) ** 2)

    g_pipe = jax.grad(loss_pipe)(stacked)
    g_ser = jax.grad(loss_serial)(stacked)
    for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                    jax.tree_util.tree_leaves(g_ser)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-4)


def test_pipeline_under_jit_with_dp():
    # jit the whole thing over a dp×pp mesh: the usual training shape.
    mesh = make_mesh(MeshConfig(dp=2, pp=4))
    per_stage = make_params(4, seed=4)
    stacked = stack_stage_params(per_stage)
    x = jax.random.normal(jax.random.PRNGKey(5), (16, HID))

    @jax.jit
    def fwd(stacked, x):
        return pipeline_sharded(stage_fn, stacked, x, mesh,
                                num_microbatches=4)

    ref = serial_apply(per_stage, x)
    np.testing.assert_allclose(np.asarray(fwd(stacked, x)),
                               np.asarray(ref), atol=1e-5, rtol=1e-5)


def test_bad_microbatch_split_raises():
    import pytest

    with pytest.raises(ValueError):
        split_microbatches(jnp.zeros((6, 4)), 4)
