"""Model + parallelism tests on the 8-device virtual CPU mesh."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tf_operator_tpu.models.llama import Llama, llama_tiny, param_logical_axes
from tf_operator_tpu.models import mnist as mnist_mod
from tf_operator_tpu.models import resnet as rn
from tf_operator_tpu.ops.layers import attention, rms_norm, apply_rope, rope_frequencies
from tf_operator_tpu.ops.ring_attention import ring_attention_sharded
from tf_operator_tpu.parallel import mesh as mesh_lib
from tf_operator_tpu.parallel.mesh import MeshConfig, make_mesh
from tf_operator_tpu.parallel.sharding import CNN_RULES, LLAMA_RULES
from tf_operator_tpu.train.trainer import (
    Trainer,
    classification_loss,
    cross_entropy_loss,
    lm_loss,
)


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh(MeshConfig(dp=2, fsdp=2, tp=2))


def test_mesh_resolution():
    cfg = MeshConfig(dp=-1, tp=2)
    sizes = cfg.resolve(8)
    assert sizes["dp"] == 4 and sizes["tp"] == 2
    with pytest.raises(ValueError, match="not divisible"):
        MeshConfig(dp=-1, tp=3).resolve(8)
    with pytest.raises(ValueError, match="at most one"):
        MeshConfig(dp=-1, tp=-1).resolve(8)


def test_mesh_has_all_axes(mesh8):
    assert mesh8.axis_names == ("dcn", "dp", "fsdp", "pp", "sp", "tp", "ep")
    assert mesh8.shape["dp"] == 2 and mesh8.shape["tp"] == 2


# ---------------------------------------------------------------------------
# ops
# ---------------------------------------------------------------------------

def test_rms_norm_matches_reference():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 8), jnp.float32)
    scale = jnp.ones(8) * 2.0
    out = rms_norm(x, scale)
    expected = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6) * 2.0
    np.testing.assert_allclose(out, expected, rtol=1e-5)


def test_rope_preserves_norm_and_relative_phase():
    angles = rope_frequencies(16, 64)
    x = jax.random.normal(jax.random.PRNGKey(1), (1, 64, 2, 16))
    rot = apply_rope(x, angles)
    np.testing.assert_allclose(np.linalg.norm(np.asarray(x), axis=-1),
                               np.linalg.norm(np.asarray(rot), axis=-1),
                               rtol=1e-4)
    # Position 0 is the identity rotation.
    np.testing.assert_allclose(np.asarray(rot[:, 0]), np.asarray(x[:, 0]),
                               rtol=1e-5, atol=1e-5)


def test_causal_attention_ignores_future():
    key = jax.random.PRNGKey(2)
    q, k, v = (jax.random.normal(kk, (1, 8, 2, 16)) for kk in jax.random.split(key, 3))
    out1 = attention(q, k, v, causal=True)
    # Perturb the last key/value: earlier positions must not change.
    k2 = k.at[:, -1].set(99.0)
    v2 = v.at[:, -1].set(99.0)
    out2 = attention(q, k2, v2, causal=True)
    np.testing.assert_allclose(out1[:, :-1], out2[:, :-1], rtol=1e-5)
    assert not np.allclose(out1[:, -1], out2[:, -1])


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_reference(causal):
    mesh = make_mesh(MeshConfig(dp=2, sp=4))
    key = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(kk, (4, 32, 2, 16), jnp.float32)
               for kk in jax.random.split(key, 3))
    ref = attention(q, k, v, causal=causal)
    ring = ring_attention_sharded(mesh, q, k, v, causal=causal,
                                  head_axis=None)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(ring),
                               rtol=2e-5, atol=2e-5)


def test_cross_entropy_matches_manual():
    logits = jnp.asarray([[2.0, 0.0, -1.0], [0.0, 1.0, 0.0]])
    targets = jnp.asarray([0, 1])
    loss = cross_entropy_loss(logits, targets)
    p = jax.nn.log_softmax(logits)
    expected = -(p[0, 0] + p[1, 1]) / 2
    np.testing.assert_allclose(loss, expected, rtol=1e-6)


# ---------------------------------------------------------------------------
# sharded training end-to-end
# ---------------------------------------------------------------------------

def _llama_trainer(mesh, cfg=None):
    cfg = cfg or llama_tiny()
    return cfg, Trainer(model=Llama(cfg), param_axes_fn=param_logical_axes,
                        rules=LLAMA_RULES, mesh=mesh,
                        optimizer=optax.adam(1e-2))


def test_llama_learns_on_3d_mesh(mesh8):
    cfg, tr = _llama_trainer(mesh8)
    rng = jax.random.PRNGKey(0)
    sample = {"inputs": jnp.zeros((8, 33), jnp.int32)}
    state, shardings = tr.init(rng, sample)

    # params actually sharded: wq kernel over (layers, embed=fsdp, heads=tp)
    wq = state.params["blocks"]["attn"]["wq"]["kernel"]
    assert wq.sharding.spec == jax.sharding.PartitionSpec(None, "fsdp", "tp")

    step = tr.make_train_step(shardings, sample)
    tok = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (8, 33)), jnp.int32)
    losses = []
    for _ in range(8):
        state, m = step(state, {"inputs": tok})
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] - 1.0
    assert int(state.step) == 8


def test_llama_ring_attention_matches_plain():
    mesh = make_mesh(MeshConfig(dp=2, sp=4))
    rng = jax.random.PRNGKey(0)
    sample = {"inputs": jnp.zeros((4, 33), jnp.int32)}
    cfg_plain, tr_plain = _llama_trainer(mesh)
    state, _ = tr_plain.init(rng, sample)
    cfg_ring = dataclasses.replace(cfg_plain, attention_impl="ring")
    tok = jnp.asarray(np.random.default_rng(1).integers(
        0, cfg_plain.vocab_size, (4, 33)), jnp.int32)
    with mesh_lib.use_mesh(mesh):
        l_plain, _ = lm_loss(state.params, None, {"inputs": tok},
                             Llama(cfg_plain).apply)
        l_ring, _ = lm_loss(state.params, None, {"inputs": tok},
                            Llama(cfg_ring).apply)
    assert abs(float(l_plain) - float(l_ring)) < 2e-3


def test_multi_step_dispatch_matches_single_steps():
    """steps_per_call=K with stacked batches computes the same training
    trajectory as K single-step dispatches (scan fusion is a dispatch
    optimization, not a semantics change), and batch shardings land on
    the batch dim (dim 1 of the stack), not the step dim."""
    mesh = make_mesh(MeshConfig(dp=-1))
    cfg = rn.resnet_tiny()

    def make():
        return Trainer(model=rn.ResNet(cfg),
                       param_axes_fn=rn.param_logical_axes,
                       rules=CNN_RULES, mesh=mesh,
                       optimizer=optax.sgd(0.1),
                       loss_fn=classification_loss)

    rng = jax.random.PRNGKey(0)
    batches = [rn.synthetic_batch(jax.random.PRNGKey(i), batch_size=16,
                                  image_size=32, num_classes=10)
               for i in range(4)]
    batches = [
        {k: jnp.asarray(v) for k, v in b.items()} for b in batches]

    tr = make()
    state, sh = tr.init(rng, batches[0])
    single = tr.make_train_step(sh, batches[0])
    for b in batches:
        state, m_single = single(state, b)

    tr2 = make()
    state2, sh2 = tr2.init(rng, batches[0])
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *batches)
    multi = tr2.make_train_step(sh2, batches[0], steps_per_call=4,
                                stacked_batches=True)
    state2, m_multi = multi(state2, stacked)

    assert int(m_multi["step"]) == int(m_single["step"])
    np.testing.assert_allclose(float(m_multi["loss"]),
                               float(m_single["loss"]),
                               rtol=1e-4, atol=1e-4)
    for a, b in zip(jax.tree.leaves(state.params),
                    jax.tree.leaves(state2.params)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=2e-3, atol=2e-3)


def test_multi_step_dispatch_same_batch_mode():
    """stacked_batches=False repeats one batch for K inner steps (the
    synthetic-bench mode): K optimizer steps happen per dispatch."""
    mesh = make_mesh(MeshConfig(dp=-1))
    cfg = rn.resnet_tiny()
    tr = Trainer(model=rn.ResNet(cfg), param_axes_fn=rn.param_logical_axes,
                 rules=CNN_RULES, mesh=mesh, optimizer=optax.adam(1e-3),
                 loss_fn=classification_loss)
    rng = jax.random.PRNGKey(0)
    batch = rn.synthetic_batch(rng, batch_size=16, image_size=32,
                               num_classes=10)
    batch = {k: jnp.asarray(v) for k, v in batch.items()}
    state, sh = tr.init(rng, batch)
    step = tr.make_train_step(sh, batch, steps_per_call=3)
    state, m = step(state, batch)
    assert int(m["step"]) == 2  # last inner step's pre-increment counter
    state, m = step(state, batch)
    assert int(m["step"]) == 5


def test_resnet_s2d_stem_exact_vs_conv7():
    """The space-to-depth stem computes the SAME function as the
    classic 7x7/stride-2 stem when its kernel is derived via
    s2d_stem_kernel (MLPerf-ResNet transform, used by the bench).
    Compared in f32 to isolate math from bf16 rounding."""
    rng = jax.random.PRNGKey(7)
    x = jax.random.normal(rng, (2, 224, 224, 3), dtype=jnp.float32)
    w7 = jax.random.normal(jax.random.PRNGKey(8), (7, 7, 3, 64),
                           dtype=jnp.float32) * 0.1

    ref = jax.lax.conv_general_dilated(
        x, w7, window_strides=(2, 2), padding=[(3, 3), (3, 3)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    w4 = rn.s2d_stem_kernel(w7)
    got = jax.lax.conv_general_dilated(
        rn.space_to_depth(x, 2), w4, window_strides=(1, 1),
        padding=[(2, 1), (2, 1)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    assert ref.shape == got.shape == (2, 112, 112, 64)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                               rtol=1e-4, atol=1e-4)


def test_resnet_s2d_stem_trains():
    mesh = make_mesh(MeshConfig(dp=-1))
    cfg = dataclasses.replace(rn.resnet_tiny(), stem="s2d")
    tr = Trainer(model=rn.ResNet(cfg), param_axes_fn=rn.param_logical_axes,
                 rules=CNN_RULES, mesh=mesh, optimizer=optax.adam(1e-3),
                 loss_fn=classification_loss)
    rng = jax.random.PRNGKey(0)
    batch = rn.synthetic_batch(rng, batch_size=16, image_size=32,
                               num_classes=10)
    batch = {k: jnp.asarray(v) for k, v in batch.items()}
    state, shardings = tr.init(rng, batch)
    step = tr.make_train_step(shardings, batch)
    losses = []
    for _ in range(5):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]


def test_resnet_trains_with_batchnorm():
    mesh = make_mesh(MeshConfig(dp=-1))
    cfg = rn.resnet_tiny()
    tr = Trainer(model=rn.ResNet(cfg), param_axes_fn=rn.param_logical_axes,
                 rules=CNN_RULES, mesh=mesh, optimizer=optax.adam(1e-3),
                 loss_fn=classification_loss)
    rng = jax.random.PRNGKey(0)
    batch = rn.synthetic_batch(rng, batch_size=16, image_size=32,
                               num_classes=10)
    batch = {k: jnp.asarray(v) for k, v in batch.items()}
    state, shardings = tr.init(rng, batch)
    assert "batch_stats" in state.extra_vars
    step = tr.make_train_step(shardings, batch)
    stats_before = jax.tree.leaves(state.extra_vars)[0].copy()
    losses = []
    for _ in range(5):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    # batch stats updated
    assert not np.allclose(stats_before, jax.tree.leaves(state.extra_vars)[0])


def test_mnist_cnn_learns():
    mesh = make_mesh(MeshConfig(dp=-1))
    tr = Trainer(model=mnist_mod.MnistCNN(),
                 param_axes_fn=rn.param_logical_axes, rules=CNN_RULES,
                 mesh=mesh, optimizer=optax.adam(3e-3),
                 loss_fn=classification_loss)
    rng = jax.random.PRNGKey(0)
    batch = mnist_mod.synthetic_batch(rng, batch_size=32)
    state, shardings = tr.init(rng, batch)
    step = tr.make_train_step(shardings, batch)
    losses = []
    for _ in range(15):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] - 0.3  # memorizes the fixed batch


@pytest.mark.parametrize("norm", ["bn_bf16", "group", "affine"])
def test_resnet_norm_variants_train(norm):
    """Every normalization scheme (docs/benchmarks.md experiment set)
    builds, trains, and reduces loss on the tiny config."""
    mesh = make_mesh(MeshConfig(dp=-1))
    cfg = dataclasses.replace(rn.resnet_tiny(), norm=norm)
    tr = Trainer(model=rn.ResNet(cfg), param_axes_fn=rn.param_logical_axes,
                 rules=CNN_RULES, mesh=mesh, optimizer=optax.adam(1e-3),
                 loss_fn=classification_loss)
    rng = jax.random.PRNGKey(0)
    batch = rn.synthetic_batch(rng, batch_size=16, image_size=32,
                               num_classes=10)
    batch = {k: jnp.asarray(v) for k, v in batch.items()}
    state, shardings = tr.init(rng, batch)
    step = tr.make_train_step(shardings, batch)
    losses = []
    for _ in range(5):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    if norm == "group":
        # GroupNorm keeps no running statistics.
        assert not state.extra_vars


def test_resnet_frozen_stats_step():
    """Interval statistics: the frozen step (update_stats=False) trains
    params, leaves batch_stats untouched, and normalizes with running
    stats (differs from the stats step's batch-stat normalization)."""
    from tf_operator_tpu.train.trainer import classification_loss_frozen_stats

    mesh = make_mesh(MeshConfig(dp=-1))
    cfg = rn.resnet_tiny()

    def trainer(loss_fn):
        return Trainer(model=rn.ResNet(cfg),
                       param_axes_fn=rn.param_logical_axes,
                       rules=CNN_RULES, mesh=mesh,
                       optimizer=optax.adam(1e-3), loss_fn=loss_fn)

    rng = jax.random.PRNGKey(0)
    batch = rn.synthetic_batch(rng, batch_size=16, image_size=32,
                               num_classes=10)
    batch = {k: jnp.asarray(v) for k, v in batch.items()}
    tr_stats = trainer(classification_loss)
    state, shardings = tr_stats.init(rng, batch)
    stats_step = tr_stats.make_train_step(shardings, batch)
    frozen_step = trainer(classification_loss_frozen_stats) \
        .make_train_step(shardings, batch)

    # One stats step to warm running stats, then a frozen step.
    state, m1 = stats_step(state, batch)
    stats_after = jax.tree.map(lambda x: np.asarray(x).copy(),
                               state.extra_vars)
    params_before = jax.tree.leaves(state.params)[0].copy()
    state, m2 = frozen_step(state, batch)
    # params moved, stats did not
    assert not np.allclose(params_before, jax.tree.leaves(state.params)[0])
    for a, b in zip(jax.tree.leaves(stats_after),
                    jax.tree.leaves(state.extra_vars)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert np.isfinite(float(m2["loss"]))


def test_tpu_batch_norm_matches_flax():
    """The TPU-formulated BN must be numerically equivalent to
    flax.linen.BatchNorm (values and updated statistics)."""
    import flax.linen as nn

    from tf_operator_tpu.ops.layers import tpu_batch_norm

    x = jax.random.normal(jax.random.PRNGKey(0), (8, 14, 14, 32),
                          jnp.float32) * 2 + 1
    m = tpu_batch_norm()
    v = m.init(jax.random.PRNGKey(1), x)
    y, upd = m.apply(v, x, mutable=["batch_stats"])
    ref = nn.BatchNorm(use_running_average=False, momentum=0.9,
                       epsilon=1e-5)
    vr = ref.init(jax.random.PRNGKey(1), x)
    yr, updr = ref.apply(vr, x, mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(y), np.asarray(yr),
                               atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(
        np.asarray(upd["batch_stats"]["mean"]),
        np.asarray(updr["batch_stats"]["mean"]), atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(upd["batch_stats"]["var"]),
        np.asarray(updr["batch_stats"]["var"]), atol=1e-4, rtol=1e-3)


class TestRingFlash:
    """Ring FLASH attention: pallas kernel per ring block + lse merge
    (ops/ring_attention.py ring_flash_attention), interpret mode on the
    CPU mesh; the chip benchmark covers the compiled path."""

    B, S, H, D = 2, 256, 4, 128

    def _qkv(self, h_kv=None, seed=0):
        ks = jax.random.split(jax.random.PRNGKey(seed), 3)
        q = jax.random.normal(ks[0], (self.B, self.S, self.H, self.D),
                              jnp.float32) * 0.3
        hk = h_kv or self.H
        k = jax.random.normal(ks[1], (self.B, self.S, hk, self.D),
                              jnp.float32) * 0.3
        v = jax.random.normal(ks[2], (self.B, self.S, hk, self.D),
                              jnp.float32) * 0.3
        return q, k, v

    @pytest.mark.parametrize("sp,causal", [(2, True), (4, True),
                                           (2, False)])
    def test_matches_dense_reference(self, sp, causal):
        from tf_operator_tpu.ops.ring_attention import ring_attention_sharded

        mesh = make_mesh(MeshConfig(sp=sp), devices=jax.devices()[:sp])
        q, k, v = self._qkv()
        ref = attention(q, k, v, causal=causal)
        out = ring_attention_sharded(mesh, q, k, v, causal=causal,
                                     head_axis=None, impl="flash")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_gradients_match_dense(self):
        from tf_operator_tpu.ops.ring_attention import ring_attention_sharded

        mesh = make_mesh(MeshConfig(sp=4), devices=jax.devices()[:4])
        q, k, v = self._qkv(seed=1)

        def loss_ring(q, k, v):
            out = ring_attention_sharded(mesh, q, k, v, causal=True,
                                         head_axis=None, impl="flash")
            return (out ** 2).mean()

        def loss_ref(q, k, v):
            return (attention(q, k, v, causal=True) ** 2).mean()

        gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", gr, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-6,
                                       err_msg=f"d{name}")

    def test_gqa_kv_heads(self):
        from tf_operator_tpu.ops.layers import repeat_kv
        from tf_operator_tpu.ops.ring_attention import ring_attention_sharded

        mesh = make_mesh(MeshConfig(sp=2), devices=jax.devices()[:2])
        q, k, v = self._qkv(h_kv=2, seed=2)
        ref = attention(q, repeat_kv(k, 2), repeat_kv(v, 2), causal=True)
        out = ring_attention_sharded(mesh, q, k, v, causal=True,
                                     head_axis=None, impl="flash")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

        # Backward with shared KV heads: the rotating dK/dV accumulators
        # carry h_kv < h heads while each query-head group folds into
        # its shared KV head.
        def loss_ring(q, k, v):
            o = ring_attention_sharded(mesh, q, k, v, causal=True,
                                       head_axis=None, impl="flash")
            return (o ** 2).mean()

        def loss_ref(q, k, v):
            o = attention(q, repeat_kv(k, 2), repeat_kv(v, 2), causal=True)
            return (o ** 2).mean()

        gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("qkv", gr, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-6,
                                       err_msg=f"gqa d{name}")

    def test_auto_routing_picks_the_right_impl(self, monkeypatch):
        """impl="auto" must actually invoke the flash ring for supported
        blocks and the einsum ring (with KV repeated for GQA) otherwise."""
        from tf_operator_tpu.ops import ring_attention as ra

        calls = []
        real_flash, real_einsum = ra.ring_flash_attention, ra.ring_attention
        monkeypatch.setattr(ra, "ring_flash_attention",
                            lambda *a, **k: calls.append("flash")
                            or real_flash(*a, **k))
        monkeypatch.setattr(ra, "ring_attention",
                            lambda *a, **k: calls.append("einsum")
                            or real_einsum(*a, **k))

        mesh = make_mesh(MeshConfig(sp=2), devices=jax.devices()[:2])
        q, k, v = self._qkv()
        ra.ring_attention_sharded(mesh, q, k, v, head_axis=None)
        assert calls[-1] == "flash"

        # D=16 cannot tile the MXU lanes -> einsum; GQA heads repeated
        # so the einsum ring does not crash on mismatched head counts.
        ks = jax.random.split(jax.random.PRNGKey(3), 3)
        q2 = jax.random.normal(ks[0], (2, 32, 4, 16), jnp.float32)
        k2 = jax.random.normal(ks[1], (2, 32, 2, 16), jnp.float32)
        v2 = jax.random.normal(ks[2], (2, 32, 2, 16), jnp.float32)
        out = ra.ring_attention_sharded(mesh, q2, k2, v2, head_axis=None)
        assert calls[-1] == "einsum"
        from tf_operator_tpu.ops.layers import repeat_kv
        ref = attention(q2, repeat_kv(k2, 2), repeat_kv(v2, 2), causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_unsupported_block_raises_clearly(self):
        from tf_operator_tpu.ops.ring_attention import ring_flash_attention

        with pytest.raises(ValueError, match="unsupported"):
            ring_flash_attention(jnp.zeros((1, 16, 2, 16)),
                                 jnp.zeros((1, 16, 2, 16)),
                                 jnp.zeros((1, 16, 2, 16)))

@pytest.mark.parametrize("policy", ["full", "save_attn", "save_qkv",
                                    "mlp_only"])
def test_llama_remat_policies_match_full(policy):
    """Round-5 remat granularity (LlamaConfig.remat_policy): every
    policy is a pure scheduling choice — identical param tree, same
    loss, same grads as whole-block remat. On CPU the flash names
    don't exist (XLA attention path), so save_attn/save_qkv degrade to
    full — which is exactly the contract: policies never change math."""
    mesh = make_mesh(MeshConfig(dp=-1))
    rng = jax.random.PRNGKey(0)
    sample = {"inputs": jnp.zeros((8, 33), jnp.int32)}
    tok = jnp.asarray(np.random.default_rng(2).integers(
        0, 256, (8, 33)), jnp.int32)

    def loss_and_grads(remat_policy):
        cfg = dataclasses.replace(llama_tiny(), remat=True,
                                  remat_policy=remat_policy)
        _, tr = _llama_trainer(mesh, cfg)
        state, sh = tr.init(rng, sample)
        step = tr.make_train_step(sh, sample)
        new_state, m = step(state, {"inputs": tok})
        return state.params, float(m["loss"]), new_state.params

    base_tree, base_loss, base_after = loss_and_grads("full")
    tree, loss, after = loss_and_grads(policy)
    assert jax.tree.structure(tree) == jax.tree.structure(base_tree)
    assert loss == pytest.approx(base_loss, rel=1e-5)
    for a, b in zip(jax.tree.leaves(after), jax.tree.leaves(base_after)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5)


def test_llama_unknown_remat_policy_rejected():
    cfg = dataclasses.replace(llama_tiny(), remat=True,
                              remat_policy="save-attn")  # typo'd value
    mesh = make_mesh(MeshConfig(dp=-1))
    _, tr = _llama_trainer(mesh, cfg)
    with pytest.raises(ValueError, match="remat_policy"):
        tr.init(jax.random.PRNGKey(0),
                {"inputs": jnp.zeros((8, 17), jnp.int32)})


# CI shard (pyproject [tool.pytest.ini_options] markers)
pytestmark = pytest.mark.compute
