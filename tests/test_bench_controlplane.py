"""Control-plane benchmark harness smoke + artifact-schema pin.

Mirrors tests/test_bench.py's role for bench.py: the harness itself is
tier-1-tested in a seconds-scale smoke configuration (5 jobs x 2 pods)
so a refactor that breaks the churn loop or silently changes the
artifact schema fails CI, not the next benchmarking round.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "benchmarks"))

import bench_controlplane  # noqa: E402

# Every key a round-over-round consumer may read. Additions are fine;
# removals/renames break the audit trail and must show up here.
ARTIFACT_KEYS = {
    "metric", "value", "unit",
    "convergence_seconds", "jobs_per_sec", "syncs", "syncs_per_sec",
    "reconcile_p50_ms", "reconcile_p99_ms", "deepcopies_per_sync",
    "jobs", "workers_per_job", "pods", "threadiness",
    "tracing", "phase_attribution",
    "env", "config_fingerprint",
}

ENV_KEYS = {"python", "machine", "system", "jax_version", "platform",
            "chip_kind"}

# The phase-attribution block (flight recorder, docs/observability.md):
# every key a "where did the time go" diff reads round-over-round.
PHASE_KEYS = {
    "queue_wait_s", "sync_s", "api_retry_s", "barrier_wait_s",
    "binder_s", "sync_breakdown_s", "sync_attributed_pct",
    "wallclock_attributed_pct",
}


def test_smoke_run_converges_and_reports():
    result = bench_controlplane.run_bench(jobs=5, workers=2,
                                          threadiness=4, timeout=30.0)
    assert result["jobs"] == 5
    assert result["pods"] == 10
    assert result["convergence_seconds"] > 0
    assert result["jobs_per_sec"] > 0
    assert result["syncs"] >= 5  # at least one sync per job
    assert result["reconcile_p99_ms"] >= result["reconcile_p50_ms"]
    # Tracing on by default: the phase-attribution block is present,
    # schema-pinned, and actually attributes the sync path.
    assert result["tracing"] is True
    pa = result["phase_attribution"]
    assert PHASE_KEYS <= set(pa)
    assert pa["sync_s"] > 0
    assert pa["queue_wait_s"] > 0
    assert set(pa["sync_breakdown_s"]) == set(
        bench_controlplane.SYNC_BREAKDOWN_SPANS)
    assert sum(pa["sync_breakdown_s"].values()) > 0
    assert 0 < pa["sync_attributed_pct"] <= 100
    # The recorder must be disabled again after the run (no bleed into
    # other scenarios or tests).
    from tf_operator_tpu.runtime import trace

    assert not trace.enabled()


def test_no_trace_run_omits_phase_block():
    result = bench_controlplane.run_bench(jobs=3, workers=2,
                                          threadiness=4, timeout=30.0,
                                          trace=False)
    assert result["tracing"] is False
    assert "phase_attribution" not in result


def test_artifact_is_one_json_line_with_pinned_schema(capsys):
    rc = bench_controlplane.main(["--jobs", "5", "--workers", "2",
                                  "--timeout", "30"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1, "artifact must be exactly one line"
    artifact = json.loads(out[0])
    assert ARTIFACT_KEYS <= set(artifact), (
        f"missing keys: {ARTIFACT_KEYS - set(artifact)}")
    assert artifact["metric"].startswith(
        "controlplane_convergence_jobs_per_sec")
    assert artifact["unit"] == "jobs/sec"
    assert artifact["value"] == artifact["jobs_per_sec"]
    assert ENV_KEYS <= set(artifact["env"])
    # Fingerprint is config-derived: same config, same fingerprint.
    assert artifact["config_fingerprint"] == (
        bench_controlplane.config_fingerprint(
            {"jobs": 5, "workers": 2, "threadiness": 4,
             "kubelet_tick": 0.01}))


def test_tenant_scenario_smoke_and_artifact_schema(capsys):
    """--tenants N contention scenario: N queues over one cohort with
    gang+quota on; the artifact carries per-queue admission-wait and
    reclaim counts. The late tenant's nominal demand lands against a
    fully borrowed cohort, so at least one reclaim must fire."""
    rc = bench_controlplane.main(["--tenants", "3", "--jobs", "2",
                                  "--workers", "2", "--timeout", "60"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1, "artifact must be exactly one line"
    artifact = json.loads(out[0])
    assert artifact["metric"].startswith(
        "controlplane_tenant_convergence_jobs_per_sec")
    assert artifact["tenants"] == 3
    assert artifact["jobs"] == 6
    assert set(artifact["per_queue"]) == {"tenant-0", "tenant-1",
                                          "tenant-2"}
    for stats in artifact["per_queue"].values():
        assert {"jobs", "admission_wait_mean_ms", "admission_wait_max_ms",
                "reclaims"} <= set(stats)
        assert stats["admission_wait_mean_ms"] is not None
    assert artifact["reclaims_total"] >= 1
    assert artifact["reclaims_total"] == sum(
        s["reclaims"] for s in artifact["per_queue"].values())
    # The late tenant waits measurably longer than the head-start ones.
    assert (artifact["per_queue"]["tenant-2"]["admission_wait_mean_ms"]
            > 0)


def test_disruption_scenario_smoke_and_artifact_schema(capsys):
    """--disruptions N goodput scenario: checkpointing fake jobs with
    injected drains through the save-before-evict barrier. Every
    disruption must resolve (acked or timed out), and because the fake
    kubelet acks barriers promptly, no steps may be lost — goodput
    stays 1.0 in smoke."""
    rc = bench_controlplane.main(["--jobs", "3", "--workers", "2",
                                  "--disruptions", "2", "--steps", "30",
                                  "--save-interval", "5",
                                  "--timeout", "90"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1, "artifact must be exactly one line"
    artifact = json.loads(out[0])
    assert artifact["metric"].startswith(
        "controlplane_disruption_goodput_ratio")
    assert artifact["unit"] == "ratio"
    assert artifact["value"] == artifact["goodput_ratio_mean"]
    assert artifact["disruptions"] == 2
    assert artifact["disruptions_injected"] == 2
    # Every injected disruption resolved through the barrier.
    assert (artifact["barriers_acked"] + artifact["barriers_timeout"]
            == 2)
    assert {"steps_lost_total", "steps_lost_per_disruption_mean",
            "goodput_ratio_mean", "goodput_ratio_min",
            "restores_observed", "steps_per_job",
            "save_interval_steps"} <= set(artifact)
    # Prompt acks in the fake kubelet: save-before-evict preserves all
    # progress, so the goodput ratio is exactly 1.0.
    assert artifact["barriers_acked"] == 2
    assert artifact["steps_lost_total"] == 0
    assert artifact["goodput_ratio_mean"] == 1.0
    assert ENV_KEYS <= set(artifact["env"])


def test_chaos_scenario_smoke_and_artifact_schema(capsys):
    """--chaos default: the full control plane (gang + barriers +
    disruptions) reconciling through the seeded FaultProfile with an
    operator crash-restart mid-run. The smoke pin: the fleet converges,
    faults were actually injected, the invariant checks come back
    EMPTY, and the artifact carries the chaos fields the acceptance
    criteria read (retry totals, degraded entries, crash count)."""
    rc = bench_controlplane.main(["--jobs", "4", "--workers", "2",
                                  "--chaos", "default",
                                  "--chaos-seed", "7",
                                  "--timeout", "120"])
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1, "artifact must be exactly one line"
    artifact = json.loads(out[0])
    assert rc == 0, artifact.get("invariant_violations",
                                 artifact.get("error"))
    assert artifact["metric"].startswith(
        "controlplane_chaos_convergence_jobs_per_sec")
    assert {"chaos_profile", "chaos_seed", "faults_injected",
            "faults_injected_total", "retries_total",
            "degraded_entries", "crash_restarts",
            "disruptions_injected", "barriers_acked",
            "barriers_timeout", "max_admitted_chips", "total_chips",
            "invariant_violations"} <= set(artifact)
    assert artifact["chaos_profile"] == "default"
    # The profile actually bit: faults were injected across classes,
    # and the default profile carries the acceptance-criteria floors
    # (>=5% write errors, >=5% conflicts).
    assert artifact["faults_injected_total"] > 0
    assert artifact["crash_restarts"] == 1
    # Disruptions are best-effort once the fleet converges; at this
    # shape at least one always lands.
    assert artifact["disruptions_injected"] >= 1
    assert (artifact["barriers_acked"] + artifact["barriers_timeout"]
            >= artifact["disruptions_injected"])
    assert artifact["invariant_violations"] == []
    assert artifact["max_admitted_chips"] <= artifact["total_chips"]
    assert ENV_KEYS <= set(artifact["env"])


def test_oversubscribe_scenario_smoke_and_artifact_schema(capsys):
    """--oversubscribe N: the SAME staggered tenant schedule run twice
    (elastic resize pass on vs static nominal allocation); the
    artifact carries both runs plus the aggregate-goodput gain. The
    tiny-shape smoke pins the mechanics, not the full acceptance
    number (that is the default shape's job): resizes actually
    happened, every shrink rode an acked barrier with ZERO committed
    steps lost, the minSlices floor held, and elastic did not lose to
    static."""
    rc = bench_controlplane.main(["--oversubscribe", "3",
                                  "--work-units", "120",
                                  "--stagger", "0.4",
                                  "--timeout", "90"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1, "artifact must be exactly one line"
    artifact = json.loads(out[0])
    assert artifact["metric"].startswith(
        "controlplane_oversubscribe_goodput_gain")
    assert artifact["unit"] == "percent"
    assert artifact["value"] == artifact["goodput_gain_pct"]
    assert artifact["tenants"] == 3
    assert artifact["cluster_chips"] == 3 * artifact["chips_per_slice"]
    for mode in ("elastic", "static"):
        stats = artifact[mode]
        assert {"makespan_seconds", "goodput_units_per_sec",
                "resizes_grow", "resizes_shrink", "barriers_acked",
                "barriers_timeout", "steps_lost_total",
                "min_slices_violations"} <= set(stats)
        assert stats["min_slices_violations"] == []
    assert artifact["static"]["resizes_grow"] == 0
    assert artifact["static"]["resizes_shrink"] == 0
    # The elastic run actually rode the machinery: at least one grow
    # into idle capacity and one barrier-gated shrink under reclaim...
    assert artifact["elastic"]["resizes_grow"] >= 1
    assert artifact["elastic"]["resizes_shrink"] >= 1
    assert artifact["elastic"]["barriers_acked"] >= 1
    # ...with zero committed steps lost across all shrinks, and the
    # elastic fleet at least matching static goodput even at a shape
    # too small to amortize the resize restarts fully.
    assert artifact["elastic"]["steps_lost_total"] == 0
    assert artifact["goodput_gain_pct"] > 0
    assert artifact["invariant_violations"] == []
    assert ENV_KEYS <= set(artifact["env"])


def test_rl_scenario_smoke_and_artifact_schema(capsys):
    """--rl: the SAME actor kill-storm schedule run twice — a
    heterogeneous gang (evict-class CPU-only actor pool beside
    barrier-class learners) vs a homogeneous control where every
    replica is a world member. The tiny-shape smoke pins the
    mechanics, not the full acceptance spread (that is the default
    shape's job): storms actually landed in both runs, the
    heterogeneous learners never restarted and their committed step
    never regressed (invariant list EMPTY), and heterogeneity beat
    the control's restart-tax goodput."""
    rc = bench_controlplane.main(["--rl", "--learners", "1",
                                  "--actors", "2",
                                  "--kill-rounds", "3",
                                  "--save-interval", "12",
                                  "--timeout", "60"])
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1, "artifact must be exactly one line"
    artifact = json.loads(out[0])
    assert rc == 0, artifact.get("invariant_violations",
                                 artifact.get("error"))
    assert artifact["metric"].startswith(
        "controlplane_rl_learner_goodput")
    assert artifact["unit"] == "ratio"
    assert artifact["value"] == artifact["learner_goodput_ratio_rl"]
    assert {"learner_goodput_ratio_rl", "learner_goodput_ratio_control",
            "goodput_gap", "rl", "control",
            "invariant_violations"} <= set(artifact)
    assert artifact["invariant_violations"] == []
    for mode in ("rl", "control"):
        stats = artifact[mode]
        assert {"heterogeneous", "goodput_ratio", "kill_rounds",
                "kills", "learner_restarts", "committed_step_final",
                "steps", "steps_executed"} <= set(stats)
        # The storms actually landed: >=half the pool per round.
        assert stats["kills"] >= stats["kill_rounds"]
    assert artifact["rl"]["heterogeneous"] is True
    assert artifact["control"]["heterogeneous"] is False
    # Actor-only churn never touched the heterogeneous learner world...
    assert artifact["rl"]["learner_restarts"] == 0
    # ...while the homogeneous control paid a world restart per storm
    # and rolled back to the last save each time.
    assert artifact["control"]["learner_restarts"] >= 1
    assert (artifact["learner_goodput_ratio_rl"]
            > artifact["learner_goodput_ratio_control"])
    assert artifact["goodput_gap"] > 0
    assert ENV_KEYS <= set(artifact["env"])


def test_sharded_scenario_smoke_and_artifact_schema(capsys):
    """--shards N: two replicas over N shard leases, a mid-run shard
    kill, zero-copy watch resume on takeover. The smoke pin: the fleet
    converges, the killed shard fails over to the standby, ownership
    evidence comes back EMPTY (every sync on the owning shard, never
    two live controllers per shard), the takeover rode the watch cache
    (hit rate 1.0 — no ADDED storm), and the artifact carries the
    sharded fields the acceptance criteria read."""
    rc = bench_controlplane.main(["--jobs", "9", "--workers", "2",
                                  "--shards", "3", "--threadiness", "3",
                                  "--timeout", "60"])
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1, "artifact must be exactly one line"
    artifact = json.loads(out[0])
    assert rc == 0, artifact.get("ownership_violations",
                                 artifact.get("error"))
    assert artifact["metric"].startswith(
        "controlplane_sharded_convergence_jobs_per_sec")
    assert {"shards", "threadiness_per_shard", "per_shard_jobs_per_sec",
            "shard_reassignments", "watch_cache_hit_rate", "shard_kill",
            "ownership_violations", "deepcopies_per_sync",
            "phase_attribution"} <= set(artifact)
    assert artifact["shards"] == 3
    assert artifact["threadiness_per_shard"] == 1
    assert set(artifact["per_shard_jobs_per_sec"]) == {"0", "1", "2"}
    assert artifact["ownership_violations"] == []
    # The kill actually happened and the standby adopted the shard.
    kill = artifact["shard_kill"]
    assert kill["enabled"] is True
    assert kill["killed_shard"] == 2
    assert kill["failover_seconds"] is not None
    assert artifact["shard_reassignments"] >= 1
    # Every shard start/takeover resumed from the watch log — zero
    # full-replay misses.
    assert artifact["watch_cache_hit_rate"] == 1.0
    assert ENV_KEYS <= set(artifact["env"])


def test_sharded_no_kill_run_skips_failover(capsys):
    rc = bench_controlplane.main(["--jobs", "4", "--workers", "2",
                                  "--shards", "2", "--no-kill-shard",
                                  "--timeout", "60"])
    assert rc == 0
    artifact = json.loads(capsys.readouterr().out.strip())
    kill = artifact["shard_kill"]
    assert kill["enabled"] is False
    assert kill["killed_shard"] is None
    assert kill["failover_seconds"] is None
    assert artifact["ownership_violations"] == []


def test_failure_still_emits_one_json_line(capsys):
    # Impossible timeout: the artifact contract holds on failure too.
    rc = bench_controlplane.main(["--jobs", "2", "--workers", "1",
                                  "--timeout", "0.000001"])
    assert rc == 1
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1
    artifact = json.loads(out[0])
    assert artifact["value"] == 0.0
    assert "error" in artifact


# CI shard (pyproject [tool.pytest.ini_options] markers)
pytestmark = pytest.mark.control_plane
