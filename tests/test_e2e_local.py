"""Hermetic end-to-end tests: real controller loop + subprocess data plane.

Reference analog: the Python e2e suites under py/kubeflow/tf_operator/
(simple_tfjob_tests, replica_restart_policy_tests, shutdown_policy_tests,
invalid_tfjob_tests, cleanpod_policy_tests) driven against a live cluster
with the test-server payload; here the whole stack runs in-process with
subprocess pods and the file-based worker stub.
"""

import json
import os
import sys
import threading
import time

import pytest

from tf_operator_tpu import testutil
from tf_operator_tpu.api import constants
from tf_operator_tpu.api.types import (
    Container,
    JobConditionType,
    PodSpec,
    PodTemplateSpec,
    ReplicaSpec,
    RestartPolicy,
    TPUJob,
    TPUJobSpec,
    ObjectMeta,
)
from tf_operator_tpu.operator import Operator
from tf_operator_tpu.runtime import store as store_mod
from tf_operator_tpu.sdk import TPUJobClient

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def stub_command(*args):
    return [sys.executable, "-m", "tf_operator_tpu.runtime.worker_stub",
            *args]


def stub_job(name, stub_dir, worker=1, args=(), restart_policy="",
             chief=0, accelerator=""):
    def spec(n):
        return ReplicaSpec(
            replicas=n,
            restart_policy=restart_policy,
            template=PodTemplateSpec(spec=PodSpec(containers=[Container(
                name=constants.DEFAULT_CONTAINER_NAME,
                command=stub_command(*args),
                env={"TPUJOB_STUB_DIR": stub_dir},
            )])))

    replica_specs = {"worker": spec(worker)}
    if chief:
        replica_specs["chief"] = spec(chief)
    job = TPUJob(metadata=ObjectMeta(name=name),
                 spec=TPUJobSpec(replica_specs=replica_specs))
    if accelerator:
        job.spec.slice.accelerator = accelerator
    return job


@pytest.fixture
def operator(tmp_path):
    op = Operator.local(workdir=REPO_ROOT)
    op.start(threadiness=2)
    yield op
    op.stop()


@pytest.fixture
def client(operator):
    return TPUJobClient(operator.store)


def tell(stub_dir, pod_name, command):
    os.makedirs(stub_dir, exist_ok=True)
    # Atomic write: the stub polls concurrently.
    tmp = os.path.join(stub_dir, f".{pod_name}.cmd.tmp")
    with open(tmp, "w") as f:
        f.write(command)
    os.replace(tmp, os.path.join(stub_dir, f"{pod_name}.cmd"))


def wait_for(predicate, timeout=15.0, interval=0.05, message="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        result = predicate()
        if result:
            return result
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {message}")


# ---------------------------------------------------------------------------


def test_simple_job_lifecycle(operator, client, tmp_path):
    """simple_tfjob_tests analog: create -> Running -> Succeeded; no
    creation-failure events; bootstrap env visible to every replica."""
    stub_dir = str(tmp_path / "stub")
    client.create(stub_job("smoke", stub_dir, worker=2,
                           args=("--exit-after", "0.5")))
    client.wait_for_condition("smoke", JobConditionType.RUNNING, timeout=10)
    # endpoints exist per replica while the job runs (they may be reaped
    # with their pods once worker-0's completion ends the job)
    wait_for(lambda: sorted(
        e.metadata.name for e in operator.store.list(store_mod.ENDPOINTS)) ==
        ["smoke-worker-0", "smoke-worker-1"], message="both endpoints")
    job = client.wait_for_job("smoke", timeout=15)
    assert testutil.check_condition(job, JobConditionType.SUCCEEDED)
    assert not operator.recorder.events_for(reason="FailedCreatePod")

    # env snapshots: both workers saw their identity + full cluster view
    for idx in (0, 1):
        with open(os.path.join(stub_dir, f"smoke-worker-{idx}.env.json")) as f:
            snap = json.load(f)
        assert snap["TPU_WORKER_ID"] == str(idx)
        assert snap["JAX_NUM_PROCESSES"] == "2"
        assert snap["JAX_COORDINATOR_ADDRESS"].startswith("127.0.0.1:")
        cluster = json.loads(snap["TPUJOB_CLUSTER_SPEC"])
        assert cluster["task"] == {"type": "worker", "index": idx}
        assert len(cluster["cluster"]["worker"]) == 2


def test_pod_names_contract(operator, client, tmp_path):
    """pod_names_validation_tests analog: {job}-{type}-{index}."""
    stub_dir = str(tmp_path / "stub")
    client.create(stub_job("names", stub_dir, worker=2, chief=1,
                           args=("--exit-after", "0.4")))
    wait_for(lambda: len(client.get_pod_names("names")) == 3,
             message="3 pods")
    assert client.get_pod_names("names") == [
        "names-chief-0", "names-worker-0", "names-worker-1"]
    assert client.get_pod_names("names", replica_type="chief") == ["names-chief-0"]
    client.wait_for_job("names", timeout=15)


def test_pod_logs_captured(operator, client, tmp_path):
    """get_logs parity: stdout of each replica is retrievable through
    the SDK (reference tf_job_client get_logs, sdk :380-446)."""
    stub_dir = str(tmp_path / "stub")
    job = stub_job("logs", stub_dir, worker=2,
                   args=("--exit-after", "0.3"))
    # Retain every pod at completion: under the default cleanPodPolicy
    # (Running) a still-running sibling is deleted when worker-0's exit
    # ends the job, and log retention follows the pod object.
    job.spec.run_policy.clean_pod_policy = "None"
    client.create(job)
    client.wait_for_job("logs", timeout=15)
    def banners_present():
        logs = client.get_job_logs("logs")
        return (sorted(logs) == ["logs-worker-0", "logs-worker-1"]
                and all(f"worker stub {name} started" in text
                        for name, text in logs.items()))
    wait_for(banners_present, message="all pod log banners")
    assert client.get_logs("logs-worker-0", tail_lines=1).count("\n") == 0
    assert client.get_logs("logs-worker-0", tail_lines=0) == ""


def test_restart_policy_exit_code_retryable(operator, client, tmp_path):
    """replica_restart_policy_tests analog: retryable exit -> same-identity
    restart (new pod uid, same name), then clean completion."""
    stub_dir = str(tmp_path / "stub")
    client.create(stub_job("restart", stub_dir, worker=2,
                           restart_policy=RestartPolicy.EXIT_CODE))
    client.wait_for_condition("restart", JobConditionType.RUNNING, timeout=10)

    pods = {p.metadata.name: p for p in client.get_pods("restart")}
    old_uid = pods["restart-worker-1"].metadata.uid

    tell(stub_dir, "restart-worker-1", "exit:137")  # SIGKILL-class: retryable

    def restarted():
        for p in client.get_pods("restart"):
            if (p.metadata.name == "restart-worker-1"
                    and p.metadata.uid != old_uid):
                return p
        return None

    wait_for(restarted, message="worker-1 restart with fresh uid")
    job = client.get("restart")
    assert not testutil.get_condition(job, JobConditionType.FAILED)

    # drive both workers to success
    wait_for(lambda: all(p.status.phase == "Running"
                         for p in client.get_pods("restart")),
             message="both running again")
    tell(stub_dir, "restart-worker-0", "exit:0")
    tell(stub_dir, "restart-worker-1", "exit:0")
    job = client.wait_for_job("restart", timeout=15)
    assert testutil.check_condition(job, JobConditionType.SUCCEEDED)


def test_restart_policy_exit_code_permanent(operator, client, tmp_path):
    """Permanent exit code under ExitCode policy -> job Failed, no restart."""
    stub_dir = str(tmp_path / "stub")
    client.create(stub_job("permfail", stub_dir, worker=1,
                           restart_policy=RestartPolicy.EXIT_CODE))
    client.wait_for_condition("permfail", JobConditionType.RUNNING, timeout=10)
    tell(stub_dir, "permfail-worker-0", "exit:1")
    job = client.wait_for_job("permfail", timeout=15)
    assert testutil.check_condition(job, JobConditionType.FAILED)


def test_shutdown_policy_chief(operator, client, tmp_path):
    """shutdown_policy_tests analog: chief completing ends the job even
    with workers still running; running workers are cleaned up."""
    stub_dir = str(tmp_path / "stub")
    client.create(stub_job("chiefdone", stub_dir, worker=2, chief=1))
    client.wait_for_condition("chiefdone", JobConditionType.RUNNING, timeout=10)
    tell(stub_dir, "chiefdone-chief-0", "exit:0")
    job = client.wait_for_job("chiefdone", timeout=15)
    assert testutil.check_condition(job, JobConditionType.SUCCEEDED)
    # CleanPodPolicy default Running: worker pods deleted after finish
    wait_for(lambda: client.get_pod_names("chiefdone", replica_type="worker") == [],
             message="workers cleaned up")


def test_invalid_job_marked_failed(operator, client, tmp_path):
    """invalid_tfjob_tests analog: bad spec -> Failed condition, no pods."""
    job = stub_job("badjob", str(tmp_path), worker=1)
    job.spec.replica_specs["worker"].template.spec.containers[0].name = "oops"
    client.create(job)
    failed = client.wait_for_condition("badjob", JobConditionType.FAILED,
                                       timeout=10)
    assert failed.status.conditions[-1].reason == "InvalidTPUJobSpec"
    assert client.get_pod_names("badjob") == []


def test_scale_down_live_job(operator, client, tmp_path):
    """Dynamic scale-down: replicas 3 -> 1 deletes out-of-range pods."""
    stub_dir = str(tmp_path / "stub")
    client.create(stub_job("scale", stub_dir, worker=3))
    wait_for(lambda: len(client.get_pod_names("scale")) == 3, message="3 pods")

    def shrink(job):
        job.spec.replica_specs["worker"].replicas = 1

    client.patch("scale", shrink)
    wait_for(lambda: client.get_pod_names("scale") == ["scale-worker-0"],
             message="scale down to worker-0")
    tell(stub_dir, "scale-worker-0", "exit:0")
    client.wait_for_job("scale", timeout=15)


def test_job_deletion_cascades_to_pods(operator, client, tmp_path):
    """Deleting a TPUJob reaps owned pods (ownerReference GC analog) and
    terminates their processes."""
    stub_dir = str(tmp_path / "stub")
    client.create(stub_job("reap", stub_dir, worker=2))
    client.wait_for_condition("reap", JobConditionType.RUNNING, timeout=10)
    client.delete("reap")
    client.wait_for_delete("reap", timeout=10)
    wait_for(lambda: client.get_pod_names("reap") == [],
             message="owned pods garbage-collected")
    assert operator.store.list(store_mod.ENDPOINTS) == []


def test_sdk_watch_streams_job_events(operator, client, tmp_path):
    """TFJobWatch analog: the watch generator streams the job's
    lifecycle and terminates on the terminal condition."""
    stub_dir = str(tmp_path / "stub")
    client.create(stub_job("watched", stub_dir, worker=1,
                           args=("--exit-after", "0.3")))
    seen = [job for _, job in client.watch("watched", timeout=15,
                                           until_finished=True)]
    assert seen, "watch yielded no events"
    assert testutil.check_condition(seen[-1], JobConditionType.SUCCEEDED)
    # lifecycle progressed: some earlier event lacked the terminal state
    assert any(not testutil.check_condition(j, JobConditionType.SUCCEEDED)
               for j in seen[:-1]) or len(seen) == 1


def test_sdk_watch_terminates_on_deletion(operator, client, tmp_path):
    """A watched job deleted mid-run is terminal for until_finished —
    no further events will ever arrive for it."""
    stub_dir = str(tmp_path / "stub")
    client.create(stub_job("shortlived", stub_dir, worker=1,
                           args=("--exit-after", "30")))
    client.wait_for_condition("shortlived", JobConditionType.RUNNING,
                              timeout=10)

    def delete_soon():
        time.sleep(0.3)
        client.delete("shortlived")

    t = threading.Thread(target=delete_soon)
    t.start()
    events = list(client.watch("shortlived", timeout=10,
                               until_finished=True))
    t.join()
    assert events and events[-1][0] == store_mod.DELETED


def test_runconfig_golden_full_topology(operator, client, tmp_path):
    """estimator_runconfig_tests analog: every replica's effective
    bootstrap config (cluster spec, task identity, rank, coordinator)
    matches expectations built from the naming contract."""
    stub_dir = str(tmp_path / "stub")
    job = stub_job("golden", stub_dir, worker=2, chief=1,
                   args=("--exit-after", "0.5"))
    job.spec.run_policy.clean_pod_policy = "None"
    client.create(job)
    client.wait_for_job("golden", timeout=15)

    def snap(rtype, idx):
        with open(os.path.join(stub_dir,
                               f"golden-{rtype}-{idx}.env.json")) as f:
            return json.load(f)

    expected_hosts = {
        "chief": ["golden-chief-0.default.svc"],
        "worker": ["golden-worker-0.default.svc",
                   "golden-worker-1.default.svc"],
    }
    # chief is process 0; workers follow (bootstrap/cluster.py ranks)
    expected_rank = {("chief", 0): 0, ("worker", 0): 1, ("worker", 1): 2}
    for (rtype, idx), rank in expected_rank.items():
        env = snap(rtype, idx)
        spec = json.loads(env["TPUJOB_CLUSTER_SPEC"])
        assert spec["task"] == {"type": rtype, "index": idx}
        got_hosts = {t: [h.rsplit(":", 1)[0] for h in hosts]
                     for t, hosts in spec["cluster"].items()}
        assert got_hosts == expected_hosts
        assert env["JAX_PROCESS_ID"] == str(rank)
        assert env["JAX_NUM_PROCESSES"] == "3"
        # coordinator is the chief's replica-0 DNS name for every replica
        assert env["JAX_COORDINATOR_ADDRESS"].split(":")[0].startswith(
            "127.0.0.1")  # localized by the process backend


def test_cleanpod_policy_all_removes_pods_e2e(operator, client, tmp_path):
    """cleanpod_policy_tests analog (All): completion deletes every pod
    and endpoint."""
    stub_dir = str(tmp_path / "stub")
    job = stub_job("cleanall", stub_dir, worker=2,
                   args=("--exit-after", "0.3"))
    job.spec.run_policy.clean_pod_policy = "All"
    client.create(job)
    client.wait_for_job("cleanall", timeout=15)
    wait_for(lambda: client.get_pod_names("cleanall") == [],
             message="pods cleaned")
    wait_for(lambda: not [
        e for e in operator.store.list(store_mod.ENDPOINTS)
        if e.metadata.name.startswith("cleanall-")],
        message="endpoints cleaned")


def test_elastic_worker_sparse_cluster_spec_e2e(operator, client, tmp_path):
    """Dynamic-worker analog (enableDynamicWorker sparse TF_CONFIG,
    reference tensorflow.go:64-83): each elastic worker sees only
    itself in the cluster view."""
    stub_dir = str(tmp_path / "stub")
    job = stub_job("elastic", stub_dir, worker=2,
                   args=("--exit-after", "0.5"))
    job.spec.enable_elastic_worker = True
    job.spec.run_policy.clean_pod_policy = "None"
    client.create(job)
    client.wait_for_job("elastic", timeout=15)
    for idx in (0, 1):
        with open(os.path.join(stub_dir,
                               f"elastic-worker-{idx}.env.json")) as f:
            env = json.load(f)
        spec = json.loads(env["TPUJOB_CLUSTER_SPEC"])
        workers = spec["cluster"]["worker"]
        assert len(workers) == 1, workers  # sparse: only itself
        assert f"elastic-worker-{idx}." in workers[0]
        assert spec["task"] == {"type": "worker", "index": idx}


def test_sparse_elastic_resize_does_not_restart_workers(operator, client,
                                                        tmp_path):
    """Reference enableDynamicWorker semantics: in sparse-elastic mode
    a worker resize must NOT restart the running workers (their sparse
    world never embedded the peers), unlike the dense-mode world
    restart. Pins the digest's resize-stability for sparse workers."""
    stub_dir = str(tmp_path / "stub")
    job = stub_job("spel", stub_dir, worker=2)
    job.spec.enable_elastic_worker = True
    job.spec.run_policy.clean_pod_policy = "None"
    client.create(job)
    client.wait_for_condition("spel", JobConditionType.RUNNING, timeout=10)
    uids_before = {p.metadata.name: p.metadata.uid
                   for p in client.get_pods("spel")}

    def grow(j):
        j.spec.replica_specs["worker"].replicas = 3

    client.patch("spel", grow)
    wait_for(lambda: len(client.get_pod_names("spel")) == 3,
             message="scaled to 3")
    time.sleep(0.5)  # give any (wrong) restart a chance to happen
    after = {p.metadata.name: p.metadata.uid
             for p in client.get_pods("spel")}
    for name, uid in uids_before.items():
        assert after.get(name) == uid, \
            f"sparse-elastic worker {name} was restarted on resize"
    assert not operator.recorder.events_for(reason="WorldResized")
    for i in range(3):
        tell(stub_dir, f"spel-worker-{i}", "exit:0")
    client.wait_for_job("spel", timeout=15)


def test_gang_scheduling_capacity_gate(tmp_path):
    """Gang admission: with capacity for one v5e-8 slice, the second job's
    pods stay Pending until the first finishes."""
    op = Operator.local(workdir=REPO_ROOT, enable_gang_scheduling=True,
                        total_chips=8)
    op.start(threadiness=2)
    try:
        client = TPUJobClient(op.store)
        stub_dir = str(tmp_path / "stub")
        client.create(stub_job("gang-a", stub_dir, worker=1,
                               accelerator="v5e-8"))
        client.wait_for_condition("gang-a", JobConditionType.RUNNING,
                                  timeout=10)
        client.create(stub_job("gang-b", stub_dir, worker=1,
                               accelerator="v5e-8",
                               args=("--exit-after", "0.3")))
        time.sleep(0.6)
        pods_b = client.get_pods("gang-b")
        assert pods_b and all(p.status.phase == "Pending" for p in pods_b), \
            "gang-b must be gated while gang-a holds the slice"
        tell(stub_dir, "gang-a-worker-0", "exit:0")
        client.wait_for_job("gang-a", timeout=15)
        job_b = client.wait_for_job("gang-b", timeout=15)
        assert testutil.check_condition(job_b, JobConditionType.SUCCEEDED)
    finally:
        op.stop()


def test_restart_resumes_from_checkpoint(operator, client, tmp_path):
    """Restart-with-resume: the reference leaves checkpointing to user
    containers (SURVEY §5 "Checkpoint/resume: none in the operator");
    here a retryable crash under the ExitCode policy restarts the
    replica at the same index and the fresh pod resumes training from
    the latest orbax checkpoint instead of step 0."""
    ckpt_dir = str(tmp_path / "ckpt")
    cmd = [sys.executable, "examples/dist_mnist/dist_mnist.py",
           "--steps", "6", "--batch-size", "16",
           "--checkpoint-dir", ckpt_dir, "--crash-at-step", "3"]
    spec = ReplicaSpec(
        replicas=1, restart_policy=RestartPolicy.EXIT_CODE,
        template=PodTemplateSpec(spec=PodSpec(containers=[Container(
            name=constants.DEFAULT_CONTAINER_NAME, command=cmd,
            env={"JAX_PLATFORMS": "cpu"})])))
    job = TPUJob(metadata=ObjectMeta(name="resume"),
                 spec=TPUJobSpec(replica_specs={"worker": spec}))
    job.spec.run_policy.clean_pod_policy = "None"
    client.create(job)
    job = client.wait_for_job("resume", timeout=180)
    assert testutil.check_condition(job, JobConditionType.SUCCEEDED)
    text = client.get_job_logs("resume")["resume-worker-0"]
    assert "injected crash at step 3" not in text  # fresh pod's log only
    assert "resumed from checkpoint at step 3" in text
    assert "done:" in text


def test_distributed_jax_two_process_training(operator, client, tmp_path):
    """True multi-process data plane: two worker pods join the
    jax.distributed coordination service through the operator-injected
    env (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID,
    the TF_CONFIG analog), build one global dp mesh, and train SPMD with
    per-process local batch shards (multihost_batch). Reference analog:
    distributed_training_tests.py, but with a real collective runtime
    instead of the Flask stub."""
    cmd = [sys.executable, "examples/dist_mnist/dist_mnist.py",
           "--steps", "3", "--batch-size", "16"]
    spec = ReplicaSpec(
        replicas=2,
        template=PodTemplateSpec(spec=PodSpec(containers=[Container(
            name=constants.DEFAULT_CONTAINER_NAME, command=cmd,
            env={"JAX_PLATFORMS": "cpu",
                 "TPUJOB_JAX_DISTRIBUTED": "1"})])))
    job = TPUJob(metadata=ObjectMeta(name="distmnist"),
                 spec=TPUJobSpec(replica_specs={"worker": spec}))
    job.spec.run_policy.clean_pod_policy = "None"
    client.create(job)
    job = client.wait_for_job("distmnist", timeout=180)
    assert testutil.check_condition(job, JobConditionType.SUCCEEDED)
    logs = client.get_job_logs("distmnist")
    assert sorted(logs) == ["distmnist-worker-0", "distmnist-worker-1"]
    # Both processes saw the global mesh; worker 0 logs the training.
    assert "distributed: 2 processes" in logs["distmnist-worker-0"]
    assert "done:" in logs["distmnist-worker-0"]
    assert "done:" in logs["distmnist-worker-1"]


def test_shutdown_policy_worker0_chiefless(operator, client, tmp_path):
    """shutdown_policy_tests analog, chiefless half: with no chief,
    worker-0's completion decides job success (reference status.go
    worker-0 semantics) while siblings still run; they are then reaped
    under the default CleanPodPolicy (Running)."""
    stub_dir = str(tmp_path / "stub")
    client.create(stub_job("w0done", stub_dir, worker=3))
    client.wait_for_condition("w0done", JobConditionType.RUNNING, timeout=10)
    tell(stub_dir, "w0done-worker-0", "exit:0")
    job = client.wait_for_job("w0done", timeout=15)
    assert testutil.check_condition(job, JobConditionType.SUCCEEDED)
    # CleanPodPolicy Running deletes the still-running siblings but keeps
    # the completed worker-0 pod (finished pods survive for log retrieval).
    wait_for(lambda: client.get_pod_names("w0done") == ["w0done-worker-0"],
             message="running siblings cleaned up")


def test_concurrent_jobs_no_duplicate_creates(operator, client, tmp_path):
    """Stress the expectations/workqueue machinery (the reference's
    subtlest code, SURVEY §7 hard part (a)): many jobs reconciled
    concurrently must create exactly one pod per replica index — a sync
    racing a stale cache would double-create without the in-flight
    expectations gate."""
    from tf_operator_tpu.runtime import metrics

    jobs, workers = 6, 3
    before = metrics.created_pods.value(job_namespace="default")
    stub_dir = str(tmp_path / "stub")
    for i in range(jobs):
        client.create(stub_job(f"burst-{i}", stub_dir, worker=workers,
                               args=("--exit-after", "0.2")))
    for i in range(jobs):
        job = client.wait_for_job(f"burst-{i}", timeout=30)
        assert testutil.check_condition(job, JobConditionType.SUCCEEDED)
    after = metrics.created_pods.value(job_namespace="default")
    assert after - before == jobs * workers, \
        f"expected {jobs * workers} creates, saw {after - before}"
    assert not operator.recorder.events_for(reason="FailedCreatePod")


def test_sdk_events_visible(operator, client, tmp_path):
    """Events persist to the store and are readable through the SDK
    (reference get_creation_failures_from_tfjob scans K8s Events)."""
    stub_dir = str(tmp_path / "stub")
    client.create(stub_job("events", stub_dir, worker=1,
                           args=("--exit-after", "0.2")))
    client.wait_for_job("events", timeout=15)
    reasons = {e.reason for e in client.get_events("events")}
    assert "SuccessfulCreatePod" in reasons or "Created" in reasons, reasons
    assert client.get_creation_failures("events") == []


def test_scale_up_live_job_elastic_env(operator, client, tmp_path):
    """Dynamic scale-up on a running elastic job: new indices appear and
    the new pod's sparse cluster spec names only itself (+ ps), the
    enableDynamicWorker contract (reference tensorflow.go:64-83)."""
    stub_dir = str(tmp_path / "stub")
    job = stub_job("grow", stub_dir, worker=1)
    job.spec.enable_elastic_worker = True
    client.create(job)
    wait_for(lambda: len(client.get_pod_names("grow")) == 1, message="1 pod")

    client.patch("grow", lambda j: setattr(
        j.spec.replica_specs["worker"], "replicas", 3))
    wait_for(lambda: len(client.get_pod_names("grow")) == 3,
             message="scale up to 3 pods")

    def snap_exists():
        path = os.path.join(stub_dir, "grow-worker-2.env.json")
        return os.path.exists(path) and path
    path = wait_for(snap_exists, message="worker-2 env snapshot")
    with open(path) as f:
        snap = json.load(f)
    cluster = json.loads(snap["TPUJOB_CLUSTER_SPEC"])
    # sparse: the worker entry carries only this replica's own address
    assert len(cluster["cluster"]["worker"]) == 1
    assert cluster["task"] == {"type": "worker", "index": 2}

    for i in range(3):
        tell(stub_dir, f"grow-worker-{i}", "exit:0")
    job = client.wait_for_job("grow", timeout=15)
    assert testutil.check_condition(job, JobConditionType.SUCCEEDED)


def test_leader_failover_completes_job(tmp_path):
    """Operator HA e2e: two control-plane instances share one store with
    leader election (reference server.go:168-193 — exactly one of N
    replicas reconciles); pods run on a separate backend (the kubelet
    analog). When the leader dies without releasing its lease, the
    standby takes over after expiry and drives a new job to completion."""
    from tf_operator_tpu.runtime.leaderelection import LeaderElector
    from tf_operator_tpu.runtime.local import LocalProcessBackend

    store = store_mod.Store()
    backend = LocalProcessBackend(
        store=store, workdir=REPO_ROOT,
        extra_env={"PYTHONPATH": REPO_ROOT + os.pathsep
                   + os.environ.get("PYTHONPATH", "")})
    backend.start()
    ops = [Operator(store=store, backend=None) for _ in range(2)]
    electors = []
    for i, op in enumerate(ops):
        electors.append(LeaderElector(
            store, identity=f"op-{i}", lease_duration=4.0,
            renew_deadline=1.0, retry_period=0.2,
            on_started_leading=lambda op=op: op.controller.run(
                threadiness=2)))
    client = TPUJobClient(store)
    stub_dir = str(tmp_path / "stub")
    try:
        electors[0].start()
        assert electors[0].wait_until_leading(timeout=5)
        electors[1].start()
        client.create(stub_job("ha-1", stub_dir, worker=1,
                               args=("--exit-after", "0.2")))
        job = client.wait_for_job("ha-1", timeout=15)
        assert testutil.check_condition(job, JobConditionType.SUCCEEDED)
        assert not electors[1].is_leader

        # Crash the leader (no release): stop its controller + thread.
        electors[0]._stop.set()
        electors[0]._thread.join(timeout=2)
        ops[0].controller.stop()

        wait_for(lambda: electors[1].is_leader, timeout=10,
                 message="standby acquires the lease")
        client.create(stub_job("ha-2", stub_dir, worker=1,
                               args=("--exit-after", "0.2")))
        job = client.wait_for_job("ha-2", timeout=15)
        assert testutil.check_condition(job, JobConditionType.SUCCEEDED)
    finally:
        for e in electors:
            e.stop()
        for op in ops:
            op.controller.stop()
        backend.stop()
        store.stop_watchers()


def test_backoff_limit_exhaustion_fails_job_e2e(operator, client, tmp_path):
    """backoffLimit at the e2e level: an OnFailure replica crash-looping
    in place accumulates container restart counts until the limit, then
    the job fails (reference PastBackoffLimit, job.go:359-396 — only
    kubelet-restarted policies count toward the limit)."""
    stub_dir = str(tmp_path / "stub")
    job = stub_job("backoff", stub_dir, worker=1,
                   restart_policy=RestartPolicy.ON_FAILURE,
                   args=("--exit-after", "0.15", "--exit-code", "1"))
    job.spec.run_policy.backoff_limit = 2
    client.create(job)
    job = client.wait_for_job("backoff", timeout=30)
    assert testutil.check_condition(job, JobConditionType.FAILED)
    cond_failed = testutil.get_condition(job, JobConditionType.FAILED)
    assert "backoff" in (cond_failed.message or "").lower() or \
           "backoff" in (cond_failed.reason or "").lower()


def test_active_deadline_fails_running_job_e2e(operator, client, tmp_path):
    """activeDeadlineSeconds at the e2e level: a healthy but slow job is
    failed once the deadline passes and its pods are torn down."""
    stub_dir = str(tmp_path / "stub")
    job = stub_job("deadline", stub_dir, worker=1)  # runs until told
    job.spec.run_policy.active_deadline_seconds = 1
    client.create(job)
    job = client.wait_for_job("deadline", timeout=30)
    assert testutil.check_condition(job, JobConditionType.FAILED)
    wait_for(lambda: client.get_pod_names("deadline") == [],
             message="pods torn down after deadline")


def test_gang_multislice_capacity_accounting(tmp_path):
    """Multislice gangs claim num_slices x slice chips: a 2-slice v5e-8
    job (16 chips) fills a 16-chip pool, gating a single-slice job until
    the multislice gang completes."""
    op = Operator.local(workdir=REPO_ROOT, enable_gang_scheduling=True,
                        total_chips=16)
    op.start(threadiness=2)
    try:
        client = TPUJobClient(op.store)
        stub_dir = str(tmp_path / "stub")
        multi = stub_job("ms-a", stub_dir, worker=2, accelerator="v5e-8")
        multi.spec.slice.num_slices = 2
        client.create(multi)
        client.wait_for_condition("ms-a", JobConditionType.RUNNING,
                                  timeout=10)
        client.create(stub_job("ms-b", stub_dir, worker=1,
                               accelerator="v5e-8",
                               args=("--exit-after", "0.3")))
        time.sleep(0.6)
        pods_b = client.get_pods("ms-b")
        assert pods_b and all(p.status.phase == "Pending" for p in pods_b), \
            "ms-b must wait while the multislice gang holds all 16 chips"
        for i in range(2):
            tell(stub_dir, f"ms-a-worker-{i}", "exit:0")
        client.wait_for_job("ms-a", timeout=15)
        job_b = client.wait_for_job("ms-b", timeout=15)
        assert testutil.check_condition(job_b, JobConditionType.SUCCEEDED)
    finally:
        op.stop()


def test_ps_job_schedules_without_warning(operator, client, tmp_path):
    """ps is a REAL role now (tf_operator_tpu.train.ps serves sharded
    async params — round-3 verdict missing-item #1 resolved by
    implementation, not deprecation): scheduling one must NOT surface
    the old no-runtime ValidationWarning. Full training coverage lives
    in tests/test_ps.py::test_e2e_ps_job_trains_async."""
    stub_dir = str(tmp_path / "stub")
    job = stub_job("ps-ok", stub_dir, worker=1)
    job.spec.replica_specs["ps"] = ReplicaSpec(
        replicas=1,
        template=PodTemplateSpec(spec=PodSpec(containers=[Container(
            name=constants.DEFAULT_CONTAINER_NAME,
            command=stub_command("--exit-after", "0.2"),
            env={"TPUJOB_STUB_DIR": stub_dir})])))
    client.create(job)
    tell(stub_dir, "ps-ok-worker-0", "exit:0")
    client.wait_for_job("ps-ok", timeout=15)
    warnings = operator.recorder.events_for(reason="ValidationWarning")
    assert not any("parameter-server" in ev.message for ev in warnings)


def test_elastic_resize_resumes_training(operator, client, tmp_path):
    """Elastic resize with REAL training (round-3 verdict ask #7): a
    2-worker jax.distributed job is scaled to 4 mid-training; the
    bootstrap-hash world restart recreates every worker with the
    4-process env, training resumes from the latest orbax checkpoint
    (not step 0), and the global batch is re-sharded across the new
    world. Reference surface: enableDynamicWorker (types.go:66-67,
    tensorflow.go:64-83) — but for the sync SPMD path, where a resize
    necessarily restarts the world."""
    ckpt_dir = str(tmp_path / "ckpt")
    cmd = [sys.executable, "examples/dist_mnist/dist_mnist.py",
           "--steps", "60", "--batch-size", "32",
           "--checkpoint-dir", ckpt_dir]
    spec = ReplicaSpec(
        replicas=2, restart_policy=RestartPolicy.ON_FAILURE,
        template=PodTemplateSpec(spec=PodSpec(containers=[Container(
            name=constants.DEFAULT_CONTAINER_NAME, command=cmd,
            env={"JAX_PLATFORMS": "cpu",
                 "TPUJOB_JAX_DISTRIBUTED": "1"})])))
    job = TPUJob(metadata=ObjectMeta(name="resize"),
                 spec=TPUJobSpec(replica_specs={"worker": spec}))
    job.spec.run_policy.clean_pod_policy = "None"
    client.create(job)

    # Resize only once real training progress is durably checkpointed.
    def checkpointed():
        try:
            return any(p.is_dir() and p.name.isdigit()
                       for p in __import__("pathlib").Path(ckpt_dir)
                       .iterdir())
        except OSError:
            return False

    wait_for(checkpointed, timeout=120,
             message="first checkpoint from the 2-worker world")

    def grow(j):
        j.spec.replica_specs["worker"].replicas = 4

    client.patch("resize", grow)
    wait_for(lambda: len(client.get_pod_names("resize")) == 4,
             timeout=30, message="4 worker pods after resize")

    job = client.wait_for_job("resize", timeout=300)
    assert testutil.check_condition(job, JobConditionType.SUCCEEDED)
    logs = client.get_job_logs("resize")
    w0 = logs["resize-worker-0"]
    # The post-resize incarnation joined a 4-process world and resumed
    # from the checkpoint instead of step 0.
    assert "distributed: 4 processes" in w0, w0[-800:]
    assert "resumed from checkpoint at step" in w0, w0[-800:]
    assert "done:" in w0
    # World-restart surfaced as an event, not silence.
    evs = operator.recorder.events_for(reason="WorldResized")
    assert evs, "no WorldResized event recorded"


def test_gang_aged_fairness_admits_large_job_under_churn(tmp_path):
    """Round-2 verdict item #9: a large job behind a stream of small
    jobs must eventually admit. With aged fairness (tiny aging window
    here), the starved large group blocks backfill, capacity drains,
    and the large job runs."""
    op = Operator.local(workdir=REPO_ROOT, enable_gang_scheduling=True,
                        total_chips=16, gang_fairness="aged",
                        gang_aging_seconds=0.5)
    op.start(threadiness=2)
    try:
        client = TPUJobClient(op.store)
        stub_dir = str(tmp_path / "stub")
        # Two small jobs hold the whole 16-chip budget.
        client.create(stub_job("small-0", stub_dir, worker=1,
                               accelerator="v5e-8"))
        client.create(stub_job("small-1", stub_dir, worker=1,
                               accelerator="v5e-8"))
        for name in ("small-0", "small-1"):
            client.wait_for_condition(name, JobConditionType.RUNNING,
                                      timeout=10)
        # The big job wants the entire budget: cannot backfill.
        client.create(stub_job("big", stub_dir, worker=2,
                               accelerator="v5e-16",
                               args=("--exit-after", "0.3")))
        # Aging is measured from the scheduler first SEEING the group
        # unadmittable, so anchor on the group's existence (the
        # controller may sync the job a beat after create).
        wait_for(lambda: op.store.try_get(store_mod.SLICEGROUPS,
                                          "default", "big") is not None,
                 message="big slice group")
        time.sleep(0.7)  # > aging window: big is now head-of-line
        pods_big = client.get_pods("big")
        assert pods_big and all(p.status.phase == "Pending"
                                for p in pods_big)
        # Churn: more small jobs arrive — they must NOT be admitted past
        # the aged big job even as capacity frees.
        client.create(stub_job("small-2", stub_dir, worker=1,
                               accelerator="v5e-8",
                               args=("--exit-after", "0.3")))
        tell(stub_dir, "small-0-worker-0", "exit:0")
        client.wait_for_job("small-0", timeout=15)
        time.sleep(0.5)
        pods_s2 = client.get_pods("small-2")
        assert pods_s2 and all(p.status.phase == "Pending"
                               for p in pods_s2), \
            "small-2 must not backfill past the aged big job"
        # Freeing the rest admits big; when big finishes, small-2 runs.
        tell(stub_dir, "small-1-worker-0", "exit:0")
        client.wait_for_job("small-1", timeout=15)
        job_big = client.wait_for_job("big", timeout=20)
        assert testutil.check_condition(job_big, JobConditionType.SUCCEEDED)
        job_s2 = client.wait_for_job("small-2", timeout=20)
        assert testutil.check_condition(job_s2, JobConditionType.SUCCEEDED)
    finally:
        op.stop()


def test_gang_strict_head_of_line_blocks_backfill(tmp_path):
    """strict fairness: nothing admits behind a non-fitting head."""
    op = Operator.local(workdir=REPO_ROOT, enable_gang_scheduling=True,
                        total_chips=16, gang_fairness="strict")
    op.start(threadiness=2)
    try:
        client = TPUJobClient(op.store)
        stub_dir = str(tmp_path / "stub")
        client.create(stub_job("holder", stub_dir, worker=1,
                               accelerator="v5e-8"))
        client.wait_for_condition("holder", JobConditionType.RUNNING,
                                  timeout=10)
        # Head of queue: needs 16 chips, only 8 free. Wait for its
        # SliceGroup to exist before submitting the next job — FIFO
        # order is group-creation order, and group creation rides the
        # controller sync, not job submission.
        client.create(stub_job("head", stub_dir, worker=2,
                               accelerator="v5e-16"))
        wait_for(lambda: op.store.try_get(store_mod.SLICEGROUPS,
                                          "default", "head") is not None,
                 message="head slice group")
        # Would fit (8 chips free) but must not jump the queue.
        client.create(stub_job("jumper", stub_dir, worker=1,
                               accelerator="v5e-8"))
        # Wait for the pods to EXIST (creation can lag under load),
        # then give admission a settle window before asserting gating.
        wait_for(lambda: client.get_pods("head")
                 and client.get_pods("jumper"), message="gated pods exist")
        time.sleep(0.8)
        for name in ("head", "jumper"):
            pods = client.get_pods(name)
            assert pods and all(p.status.phase == "Pending" for p in pods), \
                f"{name} must stay Pending under strict head-of-line"
    finally:
        op.stop()


def test_gang_infeasible_group_does_not_block_queue(tmp_path):
    """A request larger than the whole cluster can never be satisfied;
    under aged/strict fairness it must not deadlock later jobs."""
    op = Operator.local(workdir=REPO_ROOT, enable_gang_scheduling=True,
                        total_chips=8, gang_fairness="aged",
                        gang_aging_seconds=0.1)
    op.start(threadiness=2)
    try:
        client = TPUJobClient(op.store)
        stub_dir = str(tmp_path / "stub")
        # Infeasible: wants 16 chips on an 8-chip cluster.
        client.create(stub_job("toobig", stub_dir, worker=2,
                               accelerator="v5e-16"))
        time.sleep(0.4)  # > aging window
        client.create(stub_job("fits", stub_dir, worker=1,
                               accelerator="v5e-8",
                               args=("--exit-after", "0.3")))
        job = client.wait_for_job("fits", timeout=15)
        assert testutil.check_condition(job, JobConditionType.SUCCEEDED)
        pods_toobig = client.get_pods("toobig")
        assert pods_toobig and all(p.status.phase == "Pending"
                                   for p in pods_toobig)
    finally:
        op.stop()

# CI shard (pyproject [tool.pytest.ini_options] markers)
pytestmark = pytest.mark.e2e
