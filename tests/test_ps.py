"""Parameter-server runtime tests (train/ps.py).

Reference analog: the PS role of examples/v1/dist-mnist — scheduled by
the operator, trained against by workers. Here the runtime itself is
in-framework, so it gets unit coverage (sharding, async updates, wire
round-trip) plus a full e2e where 2 ps + 2 worker pods train async
MNIST through the local backend's cluster-spec loopback resolution.
"""

import os
import sys
import time

import numpy as np
import optax
import pytest

from tf_operator_tpu import testutil
from tf_operator_tpu.api import constants
from tf_operator_tpu.api.types import (
    Container,
    JobConditionType,
    ObjectMeta,
    PodSpec,
    PodTemplateSpec,
    ReplicaSpec,
    TPUJob,
    TPUJobSpec,
)
from tf_operator_tpu.operator import Operator
from tf_operator_tpu.sdk import TPUJobClient
from tf_operator_tpu.train.ps import (
    ParameterServer,
    PSClient,
    cluster_ps_addrs,
    flatten_params,
    shard_of,
    unflatten_params,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --- unit ----------------------------------------------------------------

def test_flatten_unflatten_round_trip():
    tree = {"a": {"b": np.ones((2, 3)), "c": np.zeros(4)},
            "d": np.arange(5)}
    flat = flatten_params(tree)
    assert sorted(flat) == ["a/b", "a/c", "d"]
    back = unflatten_params(flat)
    np.testing.assert_array_equal(back["a"]["b"], tree["a"]["b"])
    np.testing.assert_array_equal(back["d"], tree["d"])


def test_shard_assignment_stable_and_total():
    keys = [f"layer{i}/w" for i in range(100)]
    shards = [shard_of(k, 3) for k in keys]
    assert set(shards) <= {0, 1, 2}
    assert len(set(shards)) == 3  # spread, not degenerate
    assert shards == [shard_of(k, 3) for k in keys]  # stable


def test_single_server_applies_exact_sgd_step():
    server = ParameterServer(optimizer=optax.sgd(0.5)).serve()
    try:
        client = PSClient([f"127.0.0.1:{server.port}"])
        client.wait_ready(timeout=5)
        params = {"w": np.array([1.0, 2.0], np.float32)}
        client.init(params)
        client.push({"w": np.array([0.2, -0.2], np.float32)})
        out = client.pull()
        np.testing.assert_allclose(out["w"], [0.9, 2.1], rtol=1e-6)
    finally:
        server.stop()


def test_init_first_writer_wins():
    server = ParameterServer().serve()
    try:
        client = PSClient([f"127.0.0.1:{server.port}"])
        client.wait_ready(timeout=5)
        client.init({"w": np.zeros(2, np.float32)})
        client.init({"w": np.full(2, 9.0, np.float32)})  # loser
        np.testing.assert_array_equal(client.pull()["w"], np.zeros(2))
    finally:
        server.stop()


def test_params_sharded_across_servers():
    servers = [ParameterServer(optimizer=optax.sgd(1.0)).serve()
               for _ in range(2)]
    try:
        client = PSClient([f"127.0.0.1:{s.port}" for s in servers])
        client.wait_ready(timeout=5)
        params = {f"l{i}": {"w": np.full(2, float(i), np.float32)}
                  for i in range(8)}
        client.init(params)
        # Each server holds a strict, non-empty subset.
        counts = [len(s.pull()[0]) for s in servers]
        assert all(c > 0 for c in counts) and sum(counts) == 8
        # Push touches every shard; pull reassembles the full tree.
        client.push({k: {"w": np.ones(2, np.float32)} for k in params})
        out = client.pull()
        for i in range(8):
            np.testing.assert_allclose(out[f"l{i}"]["w"],
                                       np.full(2, float(i) - 1.0))
    finally:
        for s in servers:
            s.stop()


def test_wire_format_handles_reserved_and_odd_keys():
    """'file' collides with np.savez's first parameter; slashes and
    dots are normal in flax paths — all must round-trip."""
    from tf_operator_tpu.train.ps import _pack, _unpack

    flat = {"file": np.ones(2), "allow_pickle": np.zeros(3),
            "a/b.c/d": np.arange(4)}
    back = _unpack(_pack(flat))
    assert sorted(back) == sorted(flat)
    for k in flat:
        np.testing.assert_array_equal(back[k], flat[k])


def test_push_before_init_is_409():
    server = ParameterServer().serve()
    try:
        client = PSClient([f"127.0.0.1:{server.port}"])
        client.wait_ready(timeout=5)
        import urllib.error

        with pytest.raises(urllib.error.HTTPError) as e:
            client.push({"w": np.zeros(2, np.float32)})
        assert e.value.code == 409
    finally:
        server.stop()


# --- e2e: operator schedules ps + workers, async training converges ------

def test_e2e_ps_job_trains_async(tmp_path):
    """The reference's dist-mnist PS topology end-to-end: the operator
    schedules 2 ps + 2 worker pods, the local backend rewrites the
    cluster spec to loopback, ps pods serve real parameter shards, the
    workers train async and the job converges to Succeeded (ps pods
    reaped by CleanPodPolicy like TF parameter servers)."""
    op = Operator.local(workdir=REPO_ROOT)
    op.start(threadiness=2)
    try:
        client = TPUJobClient(op.store)

        def spec(command, n):
            return ReplicaSpec(
                replicas=n,
                template=PodTemplateSpec(spec=PodSpec(containers=[
                    Container(name=constants.DEFAULT_CONTAINER_NAME,
                              command=command,
                              env={"JAX_PLATFORMS": "cpu"})])))

        job = TPUJob(
            metadata=ObjectMeta(name="psmnist"),
            spec=TPUJobSpec(replica_specs={
                "ps": spec([sys.executable, "-m",
                            "tf_operator_tpu.train.ps", "--lr", "0.2"], 2),
                "worker": spec([sys.executable,
                                "examples/dist_mnist/dist_mnist_ps.py",
                                "--steps", "30"], 2),
            }))
        client.create(job)
        job = client.wait_for_job("psmnist", timeout=180)
        assert testutil.check_condition(job, JobConditionType.SUCCEEDED)
        logs = client.get_job_logs("psmnist")
        w0 = logs.get("psmnist-worker-0", "")
        assert "done:" in w0, w0[-500:]
        first, last = testutil.parse_ps_worker_log(w0)
        assert last < first, (first, last)
        # ps pods were reaped on completion (CleanPodPolicy Running).
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            pods = client.get_pods("psmnist")
            if not any(p.metadata.name.startswith("psmnist-ps-")
                       and p.status.phase == "Running" for p in pods):
                break
            time.sleep(0.1)
        else:
            raise AssertionError("ps pods still running after success")
    finally:
        op.stop()


def test_cluster_ps_addrs_parses_spec():
    spec = ('{"cluster": {"ps": ["127.0.0.1:41000", "127.0.0.1:41001"], '
            '"worker": ["127.0.0.1:41002"]}, '
            '"task": {"type": "worker", "index": 0}}')
    assert cluster_ps_addrs(spec) == ["127.0.0.1:41000", "127.0.0.1:41001"]
    assert cluster_ps_addrs("") == []

# CI shard (pyproject [tool.pytest.ini_options] markers)
pytestmark = pytest.mark.control_plane
