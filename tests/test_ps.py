"""Parameter-server runtime tests (train/ps.py).

Reference analog: the PS role of examples/v1/dist-mnist — scheduled by
the operator, trained against by workers. Here the runtime itself is
in-framework, so it gets unit coverage (sharding, async updates, wire
round-trip) plus a full e2e where 2 ps + 2 worker pods train async
MNIST through the local backend's cluster-spec loopback resolution.
"""

import os
import sys
import time

import numpy as np
import optax
import pytest

from tf_operator_tpu import testutil
from tf_operator_tpu.api import constants
from tf_operator_tpu.api.types import (
    Container,
    JobConditionType,
    ObjectMeta,
    PodSpec,
    PodTemplateSpec,
    ReplicaSpec,
    TPUJob,
    TPUJobSpec,
)
from tf_operator_tpu.operator import Operator
from tf_operator_tpu.sdk import TPUJobClient
from tf_operator_tpu.train.ps import (
    ParameterServer,
    PSClient,
    cluster_ps_addrs,
    flatten_params,
    shard_of,
    unflatten_params,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --- unit ----------------------------------------------------------------

def test_flatten_unflatten_round_trip():
    tree = {"a": {"b": np.ones((2, 3)), "c": np.zeros(4)},
            "d": np.arange(5)}
    flat = flatten_params(tree)
    assert sorted(flat) == ["a/b", "a/c", "d"]
    back = unflatten_params(flat)
    np.testing.assert_array_equal(back["a"]["b"], tree["a"]["b"])
    np.testing.assert_array_equal(back["d"], tree["d"])


def test_shard_assignment_stable_and_total():
    keys = [f"layer{i}/w" for i in range(100)]
    shards = [shard_of(k, 3) for k in keys]
    assert set(shards) <= {0, 1, 2}
    assert len(set(shards)) == 3  # spread, not degenerate
    assert shards == [shard_of(k, 3) for k in keys]  # stable


def test_single_server_applies_exact_sgd_step():
    server = ParameterServer(optimizer=optax.sgd(0.5)).serve()
    try:
        client = PSClient([f"127.0.0.1:{server.port}"])
        client.wait_ready(timeout=5)
        params = {"w": np.array([1.0, 2.0], np.float32)}
        client.init(params)
        client.push({"w": np.array([0.2, -0.2], np.float32)})
        out = client.pull()
        np.testing.assert_allclose(out["w"], [0.9, 2.1], rtol=1e-6)
    finally:
        server.stop()


def test_init_first_writer_wins():
    server = ParameterServer().serve()
    try:
        client = PSClient([f"127.0.0.1:{server.port}"])
        client.wait_ready(timeout=5)
        client.init({"w": np.zeros(2, np.float32)})
        client.init({"w": np.full(2, 9.0, np.float32)})  # loser
        np.testing.assert_array_equal(client.pull()["w"], np.zeros(2))
    finally:
        server.stop()


def test_params_sharded_across_servers():
    servers = [ParameterServer(optimizer=optax.sgd(1.0)).serve()
               for _ in range(2)]
    try:
        client = PSClient([f"127.0.0.1:{s.port}" for s in servers])
        client.wait_ready(timeout=5)
        params = {f"l{i}": {"w": np.full(2, float(i), np.float32)}
                  for i in range(8)}
        client.init(params)
        # Each server holds a strict, non-empty subset.
        counts = [len(s.pull()[0]) for s in servers]
        assert all(c > 0 for c in counts) and sum(counts) == 8
        # Push touches every shard; pull reassembles the full tree.
        client.push({k: {"w": np.ones(2, np.float32)} for k in params})
        out = client.pull()
        for i in range(8):
            np.testing.assert_allclose(out[f"l{i}"]["w"],
                                       np.full(2, float(i) - 1.0))
    finally:
        for s in servers:
            s.stop()


def test_wire_format_handles_reserved_and_odd_keys():
    """'file' collides with np.savez's first parameter; slashes and
    dots are normal in flax paths — all must round-trip."""
    from tf_operator_tpu.train.ps import _pack, _unpack

    flat = {"file": np.ones(2), "allow_pickle": np.zeros(3),
            "a/b.c/d": np.arange(4)}
    back = _unpack(_pack(flat))
    assert sorted(back) == sorted(flat)
    for k in flat:
        np.testing.assert_array_equal(back[k], flat[k])


def test_push_before_init_is_409():
    server = ParameterServer().serve()
    try:
        client = PSClient([f"127.0.0.1:{server.port}"])
        client.wait_ready(timeout=5)
        import urllib.error

        with pytest.raises(urllib.error.HTTPError) as e:
            client.push({"w": np.zeros(2, np.float32)})
        assert e.value.code == 409
    finally:
        server.stop()


# --- e2e: operator schedules ps + workers, async training converges ------

def test_e2e_ps_job_trains_async(tmp_path):
    """The reference's dist-mnist PS topology end-to-end: the operator
    schedules 2 ps + 2 worker pods, the local backend rewrites the
    cluster spec to loopback, ps pods serve real parameter shards, the
    workers train async and the job converges to Succeeded (ps pods
    reaped by CleanPodPolicy like TF parameter servers)."""
    op = Operator.local(workdir=REPO_ROOT)
    op.start(threadiness=2)
    try:
        client = TPUJobClient(op.store)

        def spec(command, n):
            return ReplicaSpec(
                replicas=n,
                template=PodTemplateSpec(spec=PodSpec(containers=[
                    Container(name=constants.DEFAULT_CONTAINER_NAME,
                              command=command,
                              env={"JAX_PLATFORMS": "cpu"})])))

        job = TPUJob(
            metadata=ObjectMeta(name="psmnist"),
            spec=TPUJobSpec(replica_specs={
                "ps": spec([sys.executable, "-m",
                            "tf_operator_tpu.train.ps", "--lr", "0.2"], 2),
                "worker": spec([sys.executable,
                                "examples/dist_mnist/dist_mnist_ps.py",
                                "--steps", "30"], 2),
            }))
        client.create(job)
        job = client.wait_for_job("psmnist", timeout=180)
        assert testutil.check_condition(job, JobConditionType.SUCCEEDED)
        logs = client.get_job_logs("psmnist")
        w0 = logs.get("psmnist-worker-0", "")
        assert "done:" in w0, w0[-500:]
        first, last = testutil.parse_ps_worker_log(w0)
        assert last < first, (first, last)
        # ps pods were reaped on completion (CleanPodPolicy Running).
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            pods = client.get_pods("psmnist")
            if not any(p.metadata.name.startswith("psmnist-ps-")
                       and p.status.phase == "Running" for p in pods):
                break
            time.sleep(0.1)
        else:
            raise AssertionError("ps pods still running after success")
    finally:
        op.stop()


def test_ps_token_gates_every_endpoint_but_healthz():
    """Round-5 advice: the parameter API must not be writable (or
    readable) by any pod with network reach — shared-secret bearer."""
    import urllib.error
    import urllib.request

    server = ParameterServer(optimizer=optax.sgd(0.1),
                             host="127.0.0.1", token="s3cret").serve()
    try:
        addr = f"127.0.0.1:{server.port}"
        # healthz stays open (liveness probes).
        with urllib.request.urlopen(f"http://{addr}/healthz",
                                    timeout=5) as r:
            assert r.status == 200
        anon = PSClient([addr], token="", retry_seconds=0.1)
        with pytest.raises(urllib.error.HTTPError) as err:
            anon.init({"w": np.zeros(2, np.float32)})
        assert err.value.code == 401
        wrong = PSClient([addr], token="nope", retry_seconds=0.1)
        with pytest.raises(urllib.error.HTTPError) as err:
            wrong.pull()
        assert err.value.code == 401

        good = PSClient([addr], token="s3cret")
        good.init({"w": np.ones(2, np.float32)})
        good.push({"w": np.ones(2, np.float32)})
        assert good.pull()["w"].shape == (2,)
    finally:
        server.stop()


def test_ps_state_persists_across_restart(tmp_path):
    """Round-5: a restarted shard resumes from its persisted state —
    version and parameters survive, and a racing re-init is a no-op
    (restart must not reset training)."""
    path = str(tmp_path / "shard.ckpt")
    server = ParameterServer(optimizer=optax.sgd(0.5), host="127.0.0.1",
                             state_path=path, save_interval=1).serve()
    addr = f"127.0.0.1:{server.port}"
    client = PSClient([addr])
    client.init({"w": np.zeros(4, np.float32)})
    for _ in range(3):
        client.push({"w": np.ones(4, np.float32)})
    trained = client.pull()["w"]
    server.stop()  # persists final state

    revived = ParameterServer(optimizer=optax.sgd(0.5), host="127.0.0.1",
                              state_path=path).serve()
    try:
        addr2 = f"127.0.0.1:{revived.port}"
        client2 = PSClient([addr2])
        # A worker racing the restart re-inits: first-writer-wins means
        # the RESTORED state wins, not the fresh zeros.
        client2.init({"w": np.zeros(4, np.float32)})
        np.testing.assert_allclose(client2.pull()["w"], trained)
        assert revived._version == 3
    finally:
        revived.stop()


def test_ps_corrupt_state_file_self_heals(tmp_path):
    """A truncated state file (crash mid-write on a non-fsync
    filesystem, disk corruption) must NOT crashloop the shard: it is
    quarantined and the server starts fresh, ready for first-writer
    init."""
    path = str(tmp_path / "shard.ckpt")
    with open(path, "wb") as f:
        f.write(b"\x80\x04not-a-pickle")
    server = ParameterServer(optimizer=optax.sgd(0.1), host="127.0.0.1",
                             state_path=path).serve()
    try:
        assert os.path.exists(path + ".corrupt")
        addr = f"127.0.0.1:{server.port}"
        client = PSClient([addr])
        client.init({"w": np.ones(2, np.float32)})
        np.testing.assert_allclose(client.pull()["w"], np.ones(2))
    finally:
        server.stop()


def test_ps_client_retries_through_server_restart(tmp_path):
    """A ps blip mid-training makes workers WAIT (bounded retry), not
    crash — and the revived shard serves the persisted state."""
    import threading

    path = str(tmp_path / "shard.ckpt")
    server = ParameterServer(optimizer=optax.sgd(0.1), host="127.0.0.1",
                             state_path=path, save_interval=1).serve()
    addr = f"127.0.0.1:{server.port}"
    port = server.port
    client = PSClient([addr], retry_seconds=10.0)
    client.init({"w": np.zeros(2, np.float32)})
    client.push({"w": np.ones(2, np.float32)})
    server.stop()

    revived = []

    def revive():
        time.sleep(0.5)
        revived.append(ParameterServer(
            optimizer=optax.sgd(0.1), host="127.0.0.1", port=port,
            state_path=path).serve())

    t = threading.Thread(target=revive, daemon=True)
    t.start()
    try:
        # Issued while the port is dead: must retry until the revival.
        pulled = client.pull()
        t.join()
        assert pulled["w"].shape == (2,)
    finally:
        t.join(timeout=5)
        for s in revived:
            s.stop()


def test_worker_resize_does_not_restart_ps():
    """Round-5 advice (medium): ps replicas never dial workers through
    the spec, so a worker resize must not flip their bootstrap digest
    (a ps restart would interrupt parameter serving for the whole job).
    A PS resize still restarts workers — they dial ps."""
    from tf_operator_tpu.controller.tpu_controller import (
        TPUJobController,
    )
    from tf_operator_tpu.runtime.store import Store

    plugin = TPUJobController(Store())

    def job(workers, ps):
        return testutil.new_tpujob(name="digest", worker=workers, ps=ps)

    # Worker resize: ps digest stable, worker digest flips.
    assert (plugin.bootstrap_hash(job(2, 2), "ps", 0)
            == plugin.bootstrap_hash(job(4, 2), "ps", 0))
    assert (plugin.bootstrap_hash(job(2, 2), "worker", 0)
            != plugin.bootstrap_hash(job(4, 2), "worker", 0))
    # PS resize: both flip (workers dial ps; ps serve on their list).
    assert (plugin.bootstrap_hash(job(2, 2), "ps", 0)
            != plugin.bootstrap_hash(job(2, 3), "ps", 0))
    assert (plugin.bootstrap_hash(job(2, 2), "worker", 0)
            != plugin.bootstrap_hash(job(2, 3), "worker", 0))


def test_e2e_ps_restart_mid_training_resumes(tmp_path):
    """Round-5 verdict #6: kill a ps pod MID-TRAINING. The engine
    recreates it, the revived shard restores its persisted state, the
    workers ride their retry loop through the gap, and the job still
    converges — parameter state survives the restart."""
    op = Operator.local(workdir=REPO_ROOT)
    op.start(threadiness=2)
    try:
        client = TPUJobClient(op.store)
        state_dir = str(tmp_path / "ps-state")

        def spec(command, n, env=None):
            return ReplicaSpec(
                replicas=n,
                template=PodTemplateSpec(spec=PodSpec(containers=[
                    Container(name=constants.DEFAULT_CONTAINER_NAME,
                              command=command,
                              env={"JAX_PLATFORMS": "cpu",
                                   **(env or {})})])))

        job = TPUJob(
            metadata=ObjectMeta(name="psrestart"),
            spec=TPUJobSpec(replica_specs={
                "ps": spec([sys.executable, "-m",
                            "tf_operator_tpu.train.ps", "--lr", "0.2",
                            "--state-dir", state_dir,
                            "--save-interval", "1"], 2),
                "worker": spec([sys.executable,
                                "examples/dist_mnist/dist_mnist_ps.py",
                                "--steps", "60"], 1),
            }))
        # Keep pods (and their logs) after success: the assertion reads
        # the revived ps shard's log, which CleanPodPolicy would reap.
        job.spec.run_policy.clean_pod_policy = "None"
        client.create(job)

        # Wait until training demonstrably progresses...
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            logs = client.get_job_logs("psrestart")
            if "step 5:" in logs.get("psrestart-worker-0", ""):
                break
            time.sleep(0.2)
        else:
            raise AssertionError("training never reached step 5")
        # ...then kill ps shard 0 mid-flight.
        assert op.store.try_delete(
            "pods", "default", "psrestart-ps-0"), "ps pod not found"

        got = client.wait_for_job("psrestart", timeout=180)
        assert testutil.check_condition(got, JobConditionType.SUCCEEDED)
        logs = client.get_job_logs("psrestart")
        w0 = logs.get("psrestart-worker-0", "")
        first, last = testutil.parse_ps_worker_log(w0)
        assert last < first, (first, last)
        # The revived shard really restored (not re-initialized): its
        # log says so, and its state file carries a nonzero version.
        ps0 = logs.get("psrestart-ps-0", "")
        assert "restored shard state" in ps0, ps0[-400:]
    finally:
        op.stop()


def test_cluster_ps_addrs_parses_spec():
    spec = ('{"cluster": {"ps": ["127.0.0.1:41000", "127.0.0.1:41001"], '
            '"worker": ["127.0.0.1:41002"]}, '
            '"task": {"type": "worker", "index": 0}}')
    assert cluster_ps_addrs(spec) == ["127.0.0.1:41000", "127.0.0.1:41001"]
    assert cluster_ps_addrs("") == []

# CI shard (pyproject [tool.pytest.ini_options] markers)
pytestmark = pytest.mark.control_plane
