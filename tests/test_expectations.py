"""Expectations cache tests (reference: expectation.go + pod_test.go
TestExpectation/TestExpectationWithError)."""

from tf_operator_tpu.controller.expectations import (
    ControllerExpectations,
    expectation_key,
)


def test_no_record_is_satisfied():
    e = ControllerExpectations()
    assert e.satisfied_expectations("ns/job/worker/pods")


def test_creations_block_until_observed():
    e = ControllerExpectations()
    key = expectation_key("ns/job", "pods", "worker")
    e.expect_creations(key, 2)
    assert not e.satisfied_expectations(key)
    e.creation_observed(key)
    assert not e.satisfied_expectations(key)
    e.creation_observed(key)
    assert e.satisfied_expectations(key)


def test_deletions_block_until_observed():
    e = ControllerExpectations()
    key = expectation_key("ns/job", "pods", "worker")
    e.expect_deletions(key, 1)
    assert not e.satisfied_expectations(key)
    e.deletion_observed(key)
    assert e.satisfied_expectations(key)


def test_overshoot_is_satisfied():
    e = ControllerExpectations()
    key = "k"
    e.expect_creations(key, 1)
    e.creation_observed(key)
    e.creation_observed(key)  # stray event
    assert e.satisfied_expectations(key)


def test_raise_after_failed_create():
    # Reference pod.go:243-249: a failed create decrements the expectation
    # (CreationObserved) so the controller retries; raise_expectations is the
    # inverse used by the engine before issuing creates one-by-one.
    e = ControllerExpectations()
    key = "k"
    e.expect_creations(key, 1)
    e.creation_observed(key)  # rollback after create error
    assert e.satisfied_expectations(key)
    e.raise_expectations(key, 1, 0)
    assert not e.satisfied_expectations(key)


def test_expiry_unblocks():
    e = ControllerExpectations(timeout=0.0)
    key = "k"
    e.expect_creations(key, 5)
    import time

    time.sleep(0.01)
    assert e.satisfied_expectations(key)


def test_delete_for_job_clears_prefix():
    e = ControllerExpectations()
    e.expect_creations("ns/j/worker/pods", 1)
    e.expect_creations("ns/j/ps/endpoints", 1)
    e.expect_creations("ns/j2/worker/pods", 1)
    e.delete_for_job("ns/j")
    assert e.satisfied_expectations("ns/j/worker/pods")
    assert e.satisfied_expectations("ns/j/ps/endpoints")
    assert not e.satisfied_expectations("ns/j2/worker/pods")


def test_expectation_key_layout():
    assert expectation_key("ns/j", "pods", "Worker") == "ns/j/worker/pods"
    assert expectation_key("ns/j", "pods") == "ns/j/pods"

# CI shard (pyproject [tool.pytest.ini_options] markers)
import pytest  # noqa: E402
pytestmark = pytest.mark.control_plane
