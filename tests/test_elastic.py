"""Elastic gangs: minSlices/maxSlices resize pass (docs/elastic.md).

Unit coverage of the SliceGangScheduler resize machinery:

- grow into idle capacity (job slice count + coupled worker replicas,
  biggest step that fits, self-serializing via the resizing marker);
- quota reclaim preferring shrink-to-min over displacement, and
  falling back to displacement at the floor;
- the shrink save-before-evict barrier gate (held until full-gang ack,
  `resize_barrier_seconds` observed, departed replicas' Checkpoint-
  Records pruned so they never pin committed_step);
- degraded-control-plane deferral, never-below-minSlices floors;
- slice-health drains preferring a shrink when only worker slices are
  doomed, with the atomic full drain as the fallback;
- the Resizing condition arc on the job and the resize-decision signal
  plumbing (serving_queue_depth, ROADMAP item 3a);
- flag-off parity: elastic=False never resizes anything.
"""

import datetime as dt
import json

import pytest

from tf_operator_tpu import testutil
from tf_operator_tpu.api import constants, set_defaults
from tf_operator_tpu.api.types import (
    CheckpointPolicy,
    CheckpointRecord,
    CheckpointRecordStatus,
    ClusterQueue,
    ClusterQueueSpec,
    ConditionStatus,
    HealthPolicy,
    JobConditionType,
    Node,
    NodeSpec,
    NodeStatus,
    SliceGroup,
    SliceGroupSpec,
    SliceGroupStatus,
    TenantQueue,
    TenantQueueSpec,
    TPUSliceSpec,
)
from tf_operator_tpu.api.validation import ValidationError, validate_job
from tf_operator_tpu.controller.ckpt import CheckpointCoordinator
from tf_operator_tpu.controller.engine import EngineConfig
from tf_operator_tpu.controller.gang import (
    PHASE_INQUEUE,
    PHASE_PENDING,
    PHASE_RUNNING,
    SliceGangScheduler,
)
from tf_operator_tpu.controller.health import SliceHealthController
from tf_operator_tpu.controller.quota import TenantQueueManager
from tf_operator_tpu.controller.tpu_controller import TPUJobController
from tf_operator_tpu.runtime import metrics
from tf_operator_tpu.runtime import store as store_mod
from tf_operator_tpu.runtime.store import Store

NS = "default"


def _now():
    return dt.datetime.now(dt.timezone.utc)


def make_elastic_job(store, name, num_slices=1, min_slices=1,
                     max_slices=3, accelerator="v5e-4",
                     queue="", ckpt=False):
    """Job whose worker count tracks the slice count (v5e-4 = one host
    per slice), mirroring what the resize pass scales."""
    job = testutil.new_tpujob(worker=num_slices, name=name, namespace=NS)
    job.spec.slice = TPUSliceSpec(accelerator=accelerator,
                                  num_slices=num_slices,
                                  min_slices=min_slices,
                                  max_slices=max_slices)
    if queue:
        job.spec.queue_name = queue
    if ckpt:
        job.spec.run_policy.checkpoint_policy = CheckpointPolicy(
            enabled=True, directory="/tmp/ckpt",
            barrier_timeout_seconds=30.0)
    set_defaults(job)
    store.create(store_mod.TPUJOBS, job)
    return job


def make_group(store, name, num_slices=1, min_slices=1, max_slices=3,
               accelerator="v5e-4", queue="", phase=PHASE_RUNNING,
               min_member=None):
    group = SliceGroup(
        spec=SliceGroupSpec(
            min_member=(num_slices if min_member is None else min_member),
            queue=queue,
            slice=TPUSliceSpec(accelerator=accelerator,
                               num_slices=num_slices,
                               min_slices=min_slices,
                               max_slices=max_slices)),
        status=SliceGroupStatus(phase=phase, pending_since=_now()))
    group.metadata.name = name
    group.metadata.namespace = NS
    group.metadata.labels = {constants.LABEL_JOB_NAME: name}
    store.create(store_mod.SLICEGROUPS, group)
    return group


def add_worker_pod(store, job_name, index, node="", phase="Running"):
    from tf_operator_tpu.api.types import (
        ObjectMeta,
        Pod,
        PodSpec,
        PodStatus,
    )

    pod = Pod(
        metadata=ObjectMeta(
            name=f"{job_name}-worker-{index}", namespace=NS,
            labels={constants.LABEL_JOB_NAME: job_name,
                    constants.LABEL_GROUP_NAME: constants.GROUP,
                    constants.LABEL_REPLICA_TYPE: "worker",
                    constants.LABEL_REPLICA_INDEX: str(index)},
            annotations={constants.ANNOTATION_GANG_GROUP: job_name}),
        spec=PodSpec(node_name=node),
        status=PodStatus(phase=phase))
    store.create(store_mod.PODS, pod)
    return pod


def job_slices(store, name):
    return store.get(store_mod.TPUJOBS, NS, name).spec.slice.num_slices


def worker_replicas(store, name):
    job = store.get(store_mod.TPUJOBS, NS, name)
    return job.spec.replica_specs["worker"].replicas


# --- validation -----------------------------------------------------------

def test_min_max_slices_validation():
    job = testutil.new_tpujob(worker=1)
    job.spec.slice = TPUSliceSpec(accelerator="v5e-4", num_slices=2,
                                  min_slices=1, max_slices=4)
    set_defaults(job)
    validate_job(job)  # valid elastic spec

    job.spec.slice.max_slices = 0
    with pytest.raises(ValidationError, match="maxSlices"):
        validate_job(job)

    job.spec.slice = TPUSliceSpec(accelerator="v5e-4", num_slices=1,
                                  min_slices=3, max_slices=2)
    with pytest.raises(ValidationError, match="maxSlices"):
        validate_job(job)

    job.spec.slice = TPUSliceSpec(accelerator="v5e-4", num_slices=1,
                                  min_slices=2)
    with pytest.raises(ValidationError, match="numSlices"):
        validate_job(job)

    job.spec.slice = TPUSliceSpec(num_slices=1, min_slices=1)
    with pytest.raises(ValidationError, match="accelerator"):
        validate_job(job)


# --- grow -----------------------------------------------------------------

def test_grow_into_idle_capacity_scales_job_and_workers():
    store = Store()
    make_elastic_job(store, "ela", num_slices=1, max_slices=3)
    group = make_group(store, "ela", num_slices=1, max_slices=3)
    gang = SliceGangScheduler(store, total_chips=12, elastic=True)
    before = metrics.gang_resizes.value(direction="grow", reason="idle")

    gang.readmit()

    # Biggest step that fits: 12 chips / 4 per slice -> straight to 3.
    assert job_slices(store, "ela") == 3
    assert worker_replicas(store, "ela") == 3
    group = store.get(store_mod.SLICEGROUPS, NS, "ela")
    assert group.status.resizing_reason.startswith("grow to 3")
    assert metrics.gang_resizes.value(direction="grow",
                                      reason="idle") == before + 1
    assert metrics.job_slices.value(job_namespace=NS, job="ela") == 3


def test_grow_held_while_previous_resize_settles():
    store = Store()
    make_elastic_job(store, "ela", num_slices=1, max_slices=3)
    group = make_group(store, "ela", num_slices=1, max_slices=3)
    group.status.resizing_reason = "grow to 2 slice(s): idle"
    store.update_status(store_mod.SLICEGROUPS, group)
    gang = SliceGangScheduler(store, total_chips=12, elastic=True)
    gang.readmit()
    assert job_slices(store, "ela") == 1  # held: still settling


def test_grow_stands_down_while_feasible_demand_waits():
    """Idle capacity is not idle when a feasible pending gang wants it:
    the grow pass must not starve admission."""
    store = Store()
    make_elastic_job(store, "ela", num_slices=1, max_slices=3)
    make_group(store, "ela", num_slices=1, max_slices=3)
    make_group(store, "pending", num_slices=2, min_slices=None,
               max_slices=None, phase=PHASE_PENDING)
    # Capacity fits the running gang + part of the pending one only.
    gang = SliceGangScheduler(store, total_chips=8, elastic=True)
    gang.readmit()
    assert job_slices(store, "ela") == 1
    # The pending group admitted instead (4+8 > 8 would not fit, so it
    # stays Pending — but the elastic gang must not have eaten the
    # chips it is waiting for).
    assert store.get(store_mod.SLICEGROUPS, NS,
                     "pending").status.phase == PHASE_PENDING


def test_elastic_off_never_resizes():
    store = Store()
    make_elastic_job(store, "ela", num_slices=1, max_slices=3)
    make_group(store, "ela", num_slices=1, max_slices=3)
    gang = SliceGangScheduler(store, total_chips=12, elastic=False)
    gang.readmit()
    assert job_slices(store, "ela") == 1
    assert worker_replicas(store, "ela") == 1


def test_grow_respects_degraded_control_plane():
    class DegradedHealth:
        degraded = True

        def allow_disruption(self, action):
            return False

    store = Store()
    make_elastic_job(store, "ela", num_slices=1, max_slices=3)
    make_group(store, "ela", num_slices=1, max_slices=3)
    gang = SliceGangScheduler(store, total_chips=12, elastic=True,
                              cp_health=DegradedHealth())
    gang.readmit()
    assert job_slices(store, "ela") == 1


def test_resize_signals_are_consulted_and_surfaced():
    """ROADMAP item 3a plumbing: the resize decision interface exposes
    provider signals (e.g. serving_queue_depth) on the resize record —
    the autoscaler policy itself is future work, the signal path is
    live."""
    store = Store()
    make_elastic_job(store, "ela", num_slices=1, max_slices=2)
    make_group(store, "ela", num_slices=1, max_slices=2)
    seen = []

    def signals(ns, name):
        seen.append((ns, name))
        return {"serving_queue_depth": 7.0}

    gang = SliceGangScheduler(store, total_chips=8, elastic=True,
                              resize_signals=signals)
    gang.readmit()
    assert seen == [(NS, "ela")]
    group = store.get(store_mod.SLICEGROUPS, NS, "ela")
    assert "serving_queue_depth=7" in group.status.resizing_reason


# --- shrink: quota reclaim ------------------------------------------------

def _quota_fixture(store, borrower_slices=2, borrower_min=1):
    """Cohort of two queues, nominal one slice each; the borrower gang
    holds the whole cohort, the demander's nominal demand is pending."""
    for qname in ("tenant-a", "tenant-b"):
        cq = ClusterQueue(spec=ClusterQueueSpec(nominal_chips=4,
                                                cohort="c"))
        cq.metadata.name = f"cq-{qname}"
        cq.metadata.namespace = ""
        store.create(store_mod.CLUSTERQUEUES, cq)
        tq = TenantQueue(spec=TenantQueueSpec(cluster_queue=f"cq-{qname}"))
        tq.metadata.name = qname
        tq.metadata.namespace = NS
        store.create(store_mod.TENANTQUEUES, tq)
    make_elastic_job(store, "borrower", num_slices=borrower_slices,
                     min_slices=borrower_min, max_slices=3,
                     queue="tenant-a")
    make_group(store, "borrower", num_slices=borrower_slices,
               min_slices=borrower_min, max_slices=3, queue="tenant-a")
    make_group(store, "demander", num_slices=1, min_slices=None,
               max_slices=None, queue="tenant-b", phase=PHASE_PENDING)


def test_reclaim_prefers_shrink_to_min_over_displacement():
    store = Store()
    _quota_fixture(store, borrower_slices=2, borrower_min=1)
    quota = TenantQueueManager(store)
    gang = SliceGangScheduler(store, total_chips=8, quota=quota,
                              elastic=True)
    before = metrics.gang_resizes.value(direction="shrink",
                                        reason="reclaim")

    gang.readmit()

    # The borrower was SHRUNK by exactly the demanded slice, not
    # displaced: it keeps running at the smaller size.
    assert job_slices(store, "borrower") == 1
    assert worker_replicas(store, "borrower") == 1
    group = store.get(store_mod.SLICEGROUPS, NS, "borrower")
    assert group.status.phase == PHASE_RUNNING
    assert group.status.displaced_reason == ""
    assert group.status.resizing_reason.startswith("shrink to 1")
    assert metrics.gang_resizes.value(
        direction="shrink", reason="reclaim") == before + 1


def test_reclaim_displaces_when_borrower_is_at_min_slices():
    store = Store()
    # Borrower already at its floor (min == current) but still over
    # nominal: shrink is not applicable, displacement proceeds.
    _quota_fixture(store, borrower_slices=2, borrower_min=2)
    quota = TenantQueueManager(store)
    gang = SliceGangScheduler(store, total_chips=8, quota=quota,
                              elastic=True)
    gang.readmit()
    assert job_slices(store, "borrower") == 2  # size untouched
    group = store.get(store_mod.SLICEGROUPS, NS, "borrower")
    assert group.status.phase == PHASE_PENDING  # displaced wholesale
    assert group.status.displaced_reason != ""


def test_try_shrink_refuses_below_floor():
    store = Store()
    make_elastic_job(store, "ela", num_slices=2, min_slices=2)
    make_group(store, "ela", num_slices=2, min_slices=2)
    gang = SliceGangScheduler(store, total_chips=8, elastic=True)
    assert gang.try_shrink(NS, "ela", 1, "drain", "test") is None
    assert job_slices(store, "ela") == 2


# --- shrink: save-before-evict barrier ------------------------------------

def test_shrink_waits_for_barrier_then_prunes_departed_records():
    store = Store()
    clock = [0.0]
    ckpt = CheckpointCoordinator(store, clock=lambda: clock[0])
    make_elastic_job(store, "ela", num_slices=2, min_slices=1,
                     ckpt=True)
    make_group(store, "ela", num_slices=2, min_slices=1)
    pods = [add_worker_pod(store, "ela", i) for i in range(2)]
    for i in range(2):
        rec = CheckpointRecord(status=CheckpointRecordStatus(
            step=10, progress_step=10))
        rec.metadata.name = f"ela-worker-{i}"
        rec.metadata.namespace = NS
        rec.metadata.labels = {constants.LABEL_JOB_NAME: "ela"}
        store.create(store_mod.CHECKPOINTRECORDS, rec)
    gang = SliceGangScheduler(store, total_chips=8, elastic=True,
                              ckpt=ckpt)
    barriers_before = metrics.resize_barrier_seconds.count_value(
        job_namespace=NS)

    # First ask opens the barrier: the shrink is HELD, the preemption
    # notice is stamped on the gang's pods.
    assert gang.try_shrink(NS, "ela", 1, "drain", "node doomed") is False
    assert job_slices(store, "ela") == 2
    stamped = store.get(store_mod.PODS, NS, "ela-worker-0")
    notice = stamped.metadata.annotations[
        constants.ANNOTATION_PREEMPT_NOTICE]
    barrier_id = json.loads(notice)["barrier"]

    # Full-gang ack at step 20 releases the shrink.
    for i in range(2):
        rec = store.get(store_mod.CHECKPOINTRECORDS, NS,
                        f"ela-worker-{i}")
        rec.status = CheckpointRecordStatus(step=20, progress_step=20,
                                            barrier_id=barrier_id)
        store.update_status(store_mod.CHECKPOINTRECORDS, rec)
    assert gang.try_shrink(NS, "ela", 1, "drain", "node doomed") is True
    assert job_slices(store, "ela") == 1
    assert worker_replicas(store, "ela") == 1
    assert metrics.resize_barrier_seconds.count_value(
        job_namespace=NS) == barriers_before + 1
    # The departed worker's record is pruned — left behind it would pin
    # committed_step at the shrink point forever; the survivor's stays.
    assert store.try_get(store_mod.CHECKPOINTRECORDS, NS,
                         "ela-worker-1") is None
    assert store.try_get(store_mod.CHECKPOINTRECORDS, NS,
                         "ela-worker-0") is not None
    assert ckpt.committed_step(NS, "ela") == 20


def test_out_of_world_records_never_pin_committed_step():
    """Zombie-record regression (docs/elastic.md): a doomed pod can
    publish its CheckpointRecord AFTER the shrink-time prune ran (the
    data plane races the prune), and an out-of-world record would drag
    committed_step back to the shrink point — every later restore
    would roll the surviving gang back. The coordinator must filter
    records to the job's CURRENT replica identities."""
    store = Store()
    ckpt = CheckpointCoordinator(store)
    make_elastic_job(store, "ela", num_slices=1, min_slices=1, ckpt=True)
    for name, step in (("ela-worker-0", 50), ("ela-worker-1", 20)):
        rec = CheckpointRecord(status=CheckpointRecordStatus(
            step=step, progress_step=step))
        rec.metadata.name = name
        rec.metadata.namespace = NS
        rec.metadata.labels = {constants.LABEL_JOB_NAME: "ela"}
        store.create(store_mod.CHECKPOINTRECORDS, rec)
    # worker-1 left the world (the job declares one worker): its stale
    # record must be invisible to the committed step and restore env.
    assert ckpt.committed_step(NS, "ela") == 50
    job = store.get(store_mod.TPUJOBS, NS, "ela")
    env = ckpt.bootstrap_env(job)
    assert env[constants.ENV_RESTORE_STEP] == "50"


# --- slice-health drain preference ----------------------------------------

def _health_fixture(store, num_slices=2, min_slices=1):
    job = make_elastic_job(store, "ela", num_slices=num_slices,
                           min_slices=min_slices)
    job = store.get(store_mod.TPUJOBS, NS, "ela")
    job.spec.run_policy.health_policy = HealthPolicy(enabled=True)
    store.update(store_mod.TPUJOBS, job)
    make_group(store, "ela", num_slices=num_slices,
               min_slices=min_slices)
    for name, healthy in (("node-ok", True), ("node-bad", False)):
        node = Node(spec=NodeSpec(chips=8),
                    status=NodeStatus(phase="Ready"))
        node.metadata.name = name
        if not healthy:
            node.status.conditions = {"MaintenancePending": "True"}
        store.create(store_mod.NODES, node)
    add_worker_pod(store, "ela", 0, node="node-ok")
    add_worker_pod(store, "ela", 1, node="node-bad")


def test_health_drain_prefers_shrink_for_doomed_worker_slice():
    store = Store()
    _health_fixture(store, num_slices=2, min_slices=1)
    gang = SliceGangScheduler(store, total_chips=8, elastic=True)
    health = SliceHealthController(store, gang=gang)

    health.health_pass()

    # Shrunk by the doomed slice, NOT drained: the healthy pod
    # survives, the gang stays admitted.
    assert job_slices(store, "ela") == 1
    group = store.get(store_mod.SLICEGROUPS, NS, "ela")
    assert group.status.phase == PHASE_RUNNING
    assert group.status.displaced_reason == ""
    assert store.try_get(store_mod.PODS, NS, "ela-worker-0") is not None


def test_health_drain_falls_back_when_shrink_would_break_floor():
    store = Store()
    # Both slices doomed... the floor (min=2) forbids shrinking, so the
    # atomic full drain takes over exactly as before elastic existed.
    _health_fixture(store, num_slices=2, min_slices=2)
    pod = store.get(store_mod.PODS, NS, "ela-worker-0")
    pod.spec.node_name = "node-bad"
    store.update(store_mod.PODS, pod)
    gang = SliceGangScheduler(store, total_chips=8, elastic=True)
    health = SliceHealthController(store, gang=gang)

    health.health_pass()

    assert job_slices(store, "ela") == 2  # never below the floor
    group = store.get(store_mod.SLICEGROUPS, NS, "ela")
    # Displaced wholesale (the displace may already have readmitted the
    # empty-handed group — Pending or Inqueue — but the repair arc is
    # marked and every pod was evicted).
    assert group.status.phase in (PHASE_PENDING, PHASE_INQUEUE)
    assert group.status.displaced_reason != ""
    assert store.try_get(store_mod.PODS, NS, "ela-worker-0") is None


# --- Resizing condition arc ----------------------------------------------

def test_resizing_condition_arc_on_job():
    store = Store()
    controller = TPUJobController(
        store, config=EngineConfig(enable_gang_scheduling=True),
        gang=None, namespace=NS)
    gang = SliceGangScheduler(store, total_chips=None, elastic=True)
    controller.engine.gang = gang
    gang.pod_control = controller.engine.pod_control
    job = testutil.new_tpujob(worker=1, name="ela", namespace=NS)
    job.spec.slice = TPUSliceSpec(accelerator="v5e-4", num_slices=1,
                                  min_slices=1, max_slices=2)
    store.create(store_mod.TPUJOBS, job)
    # No watchers run in this unit test, so pod-creation expectations
    # would gate every re-sync; expire them immediately.
    controller.expectations._timeout = 0.0

    controller.sync_tpujob(f"{NS}/ela")
    group = store.get(store_mod.SLICEGROUPS, NS, "ela")
    group.status.resizing_reason = "grow to 2 slice(s): idle"
    store.update_status(store_mod.SLICEGROUPS, group)

    controller.sync_tpujob(f"{NS}/ela")
    job = store.get(store_mod.TPUJOBS, NS, "ela")
    resizing = [c for c in job.status.conditions
                if c.type == JobConditionType.RESIZING]
    assert resizing and resizing[0].status == ConditionStatus.TRUE
    assert resizing[0].reason == "GangResizing"

    group = store.get(store_mod.SLICEGROUPS, NS, "ela")
    group.status.resizing_reason = ""
    store.update_status(store_mod.SLICEGROUPS, group)
    controller.sync_tpujob(f"{NS}/ela")
    job = store.get(store_mod.TPUJOBS, NS, "ela")
    resizing = [c for c in job.status.conditions
                if c.type == JobConditionType.RESIZING]
    assert resizing and resizing[0].status == ConditionStatus.FALSE
    assert resizing[0].reason == "GangResizeComplete"


# CI shard (pyproject [tool.pytest.ini_options] markers)
pytestmark = pytest.mark.control_plane
