"""CLI flag parsing + Server assembly regression tests.

Round-3 regression: Server.__init__ referenced an undefined
parse_int_map, so every `python -m tf_operator_tpu` invocation crashed
with a NameError. These tests construct Server directly (with and
without the gang flags) so the entrypoint can never ship broken again.
Reference bar: cmd/tf-operator.v1/main.go:52-68 + app/options/options.go:53-83.
"""

import argparse

import pytest

from tf_operator_tpu.cli import Server, build_parser, parse_int_map


# --- parse_int_map -------------------------------------------------------

def test_parse_int_map_empty():
    assert parse_int_map("") == {}
    assert parse_int_map("   ") == {}


def test_parse_int_map_single():
    assert parse_int_map("prod=100") == {"prod": 100}


def test_parse_int_map_multi_with_spaces():
    assert parse_int_map("prod=100, batch=10 ,best-effort=0") == {
        "prod": 100, "batch": 10, "best-effort": 0}


def test_parse_int_map_negative_and_trailing_comma():
    assert parse_int_map("low=-5,") == {"low": -5}


def test_parse_int_map_dict_passthrough():
    src = {"prod": 1}
    out = parse_int_map(src)
    assert out == src and out is not src


def test_parse_int_map_malformed_no_equals():
    with pytest.raises(argparse.ArgumentTypeError, match="malformed"):
        parse_int_map("prod")


def test_parse_int_map_malformed_empty_name():
    with pytest.raises(argparse.ArgumentTypeError, match="malformed"):
        parse_int_map("=5")


def test_parse_int_map_non_integer_value():
    with pytest.raises(argparse.ArgumentTypeError, match="not an integer"):
        parse_int_map("prod=ten")


# --- Server assembly -----------------------------------------------------

BASE = ["--monitoring-port", "0", "--no-leader-elect"]


def test_server_constructs_without_gang_flags():
    server = Server(build_parser().parse_args(BASE))
    try:
        assert server.operator is not None
    finally:
        server.shutdown()


def test_server_constructs_with_all_gang_flags():
    args = build_parser().parse_args(BASE + [
        "--enable-gang-scheduling", "--total-chips", "16",
        "--gang-fairness", "aged", "--gang-aging-seconds", "60",
        "--gang-priority-classes", "prod=100,batch=10",
        "--gang-queue-quotas", "prod=8,batch=4",
        "--gang-preemption"])
    server = Server(args)
    try:
        gang = server.operator.controller.engine.gang
        assert gang is not None
        assert gang.priority_classes == {"prod": 100, "batch": 10}
        assert gang.queue_quotas == {"prod": 8, "batch": 4}
        assert gang.preemption is True
    finally:
        server.shutdown()


def test_slice_health_flags_parse_with_defaults():
    args = build_parser().parse_args(BASE)
    assert args.slice_health is True
    assert args.health_drain_grace_seconds == 0.0
    args = build_parser().parse_args(BASE + [
        "--no-enable-slice-health",
        "--health-drain-grace-seconds", "45"])
    assert args.slice_health is False
    assert args.health_drain_grace_seconds == 45.0


def test_main_rejects_malformed_gang_map(capsys):
    """Malformed map flags must produce an argparse usage error (exit
    code 2 with the offending flag named), never a raw traceback."""
    from tf_operator_tpu.cli import main
    with pytest.raises(SystemExit) as exc:
        main(BASE + ["--gang-priority-classes", "prod=high"])
    assert exc.value.code == 2
    err = capsys.readouterr().err
    assert "--gang-priority-classes" in err
    assert "not an integer" in err


def test_enable_elastic_requires_gang_scheduling(capsys):
    from tf_operator_tpu.cli import main
    with pytest.raises(SystemExit) as exc:
        main(BASE + ["--enable-elastic"])
    assert exc.value.code == 2
    assert "--enable-gang-scheduling" in capsys.readouterr().err


def test_enable_elastic_rejected_on_kube_backend(capsys):
    """--enable-elastic on --backend kube must fail fast with a pointer
    to the node-agent open item (ROADMAP item 1): a shrink's
    save-before-evict barrier needs the notice/ack relay kubelet
    cannot provide."""
    from tf_operator_tpu.cli import main
    with pytest.raises(SystemExit) as exc:
        main(BASE + ["--enable-gang-scheduling", "--enable-elastic",
                     "--backend", "kube"])
    assert exc.value.code == 2
    err = capsys.readouterr().err
    assert "--enable-elastic" in err
    assert "node" in err and "agent" in err


def test_enable_elastic_wires_the_resize_pass():
    args = build_parser().parse_args(BASE + [
        "--enable-gang-scheduling", "--enable-elastic",
        "--total-chips", "16"])
    server = Server(args)
    try:
        gang = server.operator.controller.engine.gang
        assert gang is not None and gang.elastic is True
    finally:
        server.shutdown()


def test_elastic_off_by_default():
    args = build_parser().parse_args(BASE + ["--enable-gang-scheduling"])
    server = Server(args)
    try:
        assert server.operator.controller.engine.gang.elastic is False
    finally:
        server.shutdown()


def test_serving_autoscaler_requires_serving_and_elastic(capsys):
    from tf_operator_tpu.cli import main
    with pytest.raises(SystemExit) as exc:
        main(BASE + ["--enable-serving-autoscaler"])
    assert exc.value.code == 2
    err = capsys.readouterr().err
    assert "--enable-serving" in err and "--enable-elastic" in err


def test_serving_autoscaler_rejected_on_kube_backend(capsys):
    """It rides the elastic resize pass, which kube does not run yet
    (docs/serving.md): fail fast rather than silently never scaling."""
    from tf_operator_tpu.cli import main
    with pytest.raises(SystemExit) as exc:
        main(BASE + ["--enable-gang-scheduling", "--enable-elastic",
                     "--enable-serving", "--enable-serving-autoscaler",
                     "--backend", "kube"])
    assert exc.value.code == 2
    err = capsys.readouterr().err
    assert "--enable-serving-autoscaler" in err and "kube" in err


def test_serving_gateway_needs_spool(capsys):
    from tf_operator_tpu.cli import main
    with pytest.raises(SystemExit) as exc:
        main(BASE + ["--enable-serving-gateway"])
    assert exc.value.code == 2
    assert "--gateway-spool" in capsys.readouterr().err


def test_serving_front_door_wires_up(tmp_path):
    """Gateway + autoscaler assembly: the gateway fronts the given
    spool and the autoscaler is handed the gang scheduler AND serves as
    its resize-signal provider (the wiring docs/serving.md promises)."""
    args = build_parser().parse_args(BASE + [
        "--enable-gang-scheduling", "--enable-elastic",
        "--enable-serving", "--enable-serving-autoscaler",
        "--enable-serving-gateway", "--gateway-port", "0",
        "--gateway-spool", str(tmp_path / "spool")])
    server = Server(args)
    try:
        autoscaler = server.operator.autoscaler
        gang = server.operator.controller.engine.gang
        assert autoscaler is not None
        assert autoscaler.gang is gang
        assert gang.resize_signals == autoscaler.signals
        assert server.gateway is not None
        assert server.gateway.spool.root == str(tmp_path / "spool")
    finally:
        server.shutdown()


def test_version_wins_over_backend_validation(capsys):
    """`--version` prints and exits even when combined with flags that
    would otherwise fail validation (e.g. --backend none w/o api-port)."""
    from tf_operator_tpu.cli import main
    assert main(["--backend", "none", "--version"]) == 0
    assert "tpu-operator" in capsys.readouterr().out

# CI shard (pyproject [tool.pytest.ini_options] markers)
pytestmark = pytest.mark.control_plane
