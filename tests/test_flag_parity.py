"""Flag/backend parity gate: hack/verify-flag-parity.py under tier-1.

Every --enable-* kube gate in cli.py must cite an existing docs page
that explains the gate, and no doc may keep claiming a flag is
rejected on --backend kube after the gate was lifted (the node-agent
round lifted tenant queues, checkpoint coordination, and serving —
only elastic remains gated; see docs/node-agent.md).
"""

from __future__ import annotations

import importlib.util
import os

import pytest

_SCRIPT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "hack", "verify-flag-parity.py")


def _load():
    spec = importlib.util.spec_from_file_location("verify_flag_parity",
                                                  _SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_cli_and_docs_agree():
    mod = _load()
    assert mod.check() == []


def test_checker_sees_the_real_contract():
    """The gate is only as good as its parser: it must see the real
    flag set and the remaining kube gates (an empty parse would make
    test_cli_and_docs_agree pass vacuously)."""
    mod = _load()
    flags = mod.enable_flags()
    gates = mod.kube_gates()
    assert {"--enable-gang-scheduling", "--enable-tenant-queues",
            "--enable-ckpt-coordination", "--enable-serving",
            "--enable-elastic"} <= flags
    # The node-agent relay lifted every kube gate except elastic — the
    # serving autoscaler rides the elastic resize pass, so it inherits
    # the same gate (docs/serving.md) — and shard leases live in the
    # in-process store, so --shards > 1 is rejected on kube until the
    # kube lease client lands (docs/robustness.md).
    assert set(gates) == {"--enable-elastic",
                          "--enable-serving-autoscaler",
                          "--shards"}
    message, cited = gates["--enable-elastic"]
    assert "elastic.md" in "".join(cited)
    # The lifted flags must NOT be gated anymore.
    for lifted in ("--enable-tenant-queues", "--enable-ckpt-coordination",
                   "--enable-serving"):
        assert lifted not in gates


def test_checker_reports_drift(tmp_path):
    """A doctored cli (gate citing a missing doc) and a doctored doc
    (stale rejection claim for an ungated flag) both surface."""
    mod = _load()
    with open(os.path.join(os.path.dirname(_SCRIPT), "..",
                           "tf_operator_tpu", "cli.py"),
              encoding="utf-8") as f:
        src = f.read()
    doctored_cli = tmp_path / "cli.py"
    doctored_cli.write_text(src + '\n\ndef _fake(parser, args):\n'
                            '    parser.error("--enable-serving is not '
                            'supported with --backend kube; see '
                            'docs/ghost.md")\n', encoding="utf-8")
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "stale.md").write_text(
        "The `--enable-tenant-queues` flag is rejected on `--backend "
        "kube` (no CRD mirror yet).\n", encoding="utf-8")
    problems = mod.check(str(doctored_cli), str(docs))
    assert any("docs/ghost.md" in p for p in problems)
    assert any("--enable-tenant-queues" in p and "stale.md" in p
               for p in problems)


# CI shard (pyproject [tool.pytest.ini_options] markers)
pytestmark = pytest.mark.control_plane
