"""Native batch generator + data pipeline tests."""

import numpy as np
import pytest

from tf_operator_tpu import native
from tf_operator_tpu.train.data import DeviceFeeder, SyntheticImages, SyntheticLM


def test_native_library_builds_and_loads():
    assert native.available(), "libbatchgen.so should build with g++"


def test_fill_uniform_distribution():
    x = native.fill_uniform((1 << 16,), seed=42)
    assert x.dtype == np.float32
    assert 0.0 <= x.min() and x.max() < 1.0
    assert abs(float(x.mean()) - 0.5) < 0.01


def test_fill_randint_range_and_coverage():
    x = native.fill_randint((1 << 14,), 3, 11, seed=7)
    assert x.dtype == np.int32
    assert x.min() >= 3 and x.max() <= 10
    assert set(np.unique(x)) == set(range(3, 11))


def test_normalize_matches_numpy():
    rng = np.random.default_rng(0)
    img = rng.integers(0, 256, (4, 8, 8, 3), dtype=np.uint8)
    mean = [0.485, 0.456, 0.406]
    std = [0.229, 0.224, 0.225]
    out = native.normalize_images(img, mean, std)
    expected = (img.astype(np.float32) / 255.0 - np.asarray(mean, np.float32)) \
        / np.asarray(std, np.float32)
    np.testing.assert_allclose(out, expected, rtol=1e-5, atol=1e-6)


def test_synthetic_iterators_shapes():
    lm = iter(SyntheticLM(batch_size=4, seq_len=16, vocab_size=100))
    b = next(lm)
    assert b["inputs"].shape == (4, 17)
    assert b["inputs"].max() < 100
    img = iter(SyntheticImages(batch_size=2, image_size=8, num_classes=5))
    b = next(img)
    assert b["inputs"].shape == (2, 8, 8, 3)


def test_device_feeder_finite_iterator_raises_stopiteration():
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tf_operator_tpu.parallel.mesh import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(dp=-1))
    sharding = {"inputs": NamedSharding(mesh, P())}
    batches = [{"inputs": np.ones((2, 2), np.float32)} for _ in range(3)]
    feeder = DeviceFeeder(iter(batches), sharding)
    out = list(feeder)
    assert len(out) == 3
    feeder.stop()


# ---------------------------------------------------------------------------
# Prefetching loader (libloader.so)
# ---------------------------------------------------------------------------

def test_prefetch_loader_builds_and_loads():
    from tf_operator_tpu.native import prefetch

    assert prefetch.available(), "libloader.so should build with g++"


def test_prefetch_deterministic_across_configs():
    # Batch contents depend only on (seed, batch_index), never on the
    # thread count or ring depth.
    from tf_operator_tpu.native import prefetch

    with prefetch.create_tokens(4, 16, 1000, depth=2, threads=4,
                                seed=3) as a, \
         prefetch.create_tokens(4, 16, 1000, depth=8, threads=1,
                                seed=3) as b:
        for _ in range(10):
            np.testing.assert_array_equal(next(a)["inputs"],
                                          next(b)["inputs"])


def test_prefetch_runs_ahead_of_consumer():
    import time

    from tf_operator_tpu.native import prefetch

    with prefetch.create_tokens(8, 64, 100, depth=4, threads=2) as ld:
        next(ld)
        deadline = time.monotonic() + 2.0
        while ld.produced() < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
        # ring refilled in the background without further consumption
        assert ld.produced() >= 3


def test_prefetch_images_shapes_and_ranges():
    from tf_operator_tpu.native import prefetch

    with prefetch.create_images(2, 16, num_classes=7, threads=2) as ld:
        batch = next(ld)
    assert batch["inputs"].shape == (2, 16, 16, 3)
    assert batch["inputs"].dtype == np.float32
    assert 0.0 <= batch["inputs"].min() and batch["inputs"].max() < 1.0
    assert batch["labels"].shape == (2,)
    assert 0 <= batch["labels"].min() and batch["labels"].max() < 7


def test_prefetch_close_stops_iteration():
    from tf_operator_tpu.native import prefetch

    ld = prefetch.create_tokens(2, 8, 10)
    next(ld)
    ld.close()
    ld.close()  # idempotent
    with pytest.raises(StopIteration):
        next(ld)


def test_pipelines_yield_trainer_format():
    from tf_operator_tpu.train.data import images_pipeline, lm_pipeline

    it = lm_pipeline(4, 16, 100)
    batch = next(iter(it))
    assert batch["inputs"].shape == (4, 17)  # S+1 for the shift
    getattr(it, "close", lambda: None)()

    it = images_pipeline(2, 16, 10)
    batch = next(iter(it))
    assert set(batch) == {"inputs", "labels"}
    getattr(it, "close", lambda: None)()


# --- async double-buffered host->device prefetch (ROADMAP item 5) -------

def _device_sharding():
    import jax

    return jax.sharding.SingleDeviceSharding(jax.devices("cpu")[0])


def test_prefetch_to_device_preserves_order_and_places():
    import jax

    from tf_operator_tpu.train.data import prefetch_to_device

    src = [{"x": np.full((2,), i, np.int32)} for i in range(7)]
    out = list(prefetch_to_device(iter(src), {"x": _device_sharding()},
                                  depth=2))
    assert [int(b["x"][0]) for b in out] == list(range(7))
    assert all(isinstance(b["x"], jax.Array) for b in out)


def test_prefetch_to_device_stays_one_ahead_not_greedy():
    # Double buffering pulls at most `depth` batches beyond the one the
    # consumer holds — it must never drain the source greedily (that
    # would defeat backpressure and buffer the whole epoch on device).
    from tf_operator_tpu.train.data import prefetch_to_device

    pulled = []

    def source():
        for i in range(10):
            pulled.append(i)
            yield {"x": np.full((2,), i, np.int32)}

    it = prefetch_to_device(source(), {"x": _device_sharding()}, depth=2)
    next(it)
    assert len(pulled) <= 4  # 1 consumed + <= depth+1 staged
    next(it)
    assert len(pulled) <= 5
    assert sum(1 for _ in it) == 8  # remainder, in order, no loss


def test_prefetch_to_device_short_iterator_and_empty():
    from tf_operator_tpu.train.data import prefetch_to_device

    sharding = {"x": _device_sharding()}
    one = [{"x": np.zeros((1,), np.float32)}]
    assert len(list(prefetch_to_device(iter(one), sharding, depth=4))) == 1
    assert list(prefetch_to_device(iter([]), sharding, depth=2)) == []


def test_run_train_steps_prefetch_flag_feeds_same_batches():
    # Flag-guarded integration: run_train_steps(prefetch_sharding=...)
    # must feed the exact same batch sequence as the unprefetched loop.
    from tf_operator_tpu.train.trainer import run_train_steps

    seen = []

    def step_fn(state, batch):
        seen.append(int(batch["x"][0]))
        return state + 1, {"loss": 0.0}

    src = [{"x": np.full((2,), i, np.int32)} for i in range(5)]
    state = run_train_steps(step_fn, 0, iter(src), num_steps=5,
                            prefetch_sharding={"x": _device_sharding()})
    assert state == 5
    assert seen == list(range(5))


# CI shard (pyproject [tool.pytest.ini_options] markers)
pytestmark = pytest.mark.compute
