"""Native batch generator + data pipeline tests."""

import numpy as np
import pytest

from tf_operator_tpu import native
from tf_operator_tpu.train.data import DeviceFeeder, SyntheticImages, SyntheticLM


def test_native_library_builds_and_loads():
    assert native.available(), "libbatchgen.so should build with g++"


def test_fill_uniform_distribution():
    x = native.fill_uniform((1 << 16,), seed=42)
    assert x.dtype == np.float32
    assert 0.0 <= x.min() and x.max() < 1.0
    assert abs(float(x.mean()) - 0.5) < 0.01


def test_fill_randint_range_and_coverage():
    x = native.fill_randint((1 << 14,), 3, 11, seed=7)
    assert x.dtype == np.int32
    assert x.min() >= 3 and x.max() <= 10
    assert set(np.unique(x)) == set(range(3, 11))


def test_normalize_matches_numpy():
    rng = np.random.default_rng(0)
    img = rng.integers(0, 256, (4, 8, 8, 3), dtype=np.uint8)
    mean = [0.485, 0.456, 0.406]
    std = [0.229, 0.224, 0.225]
    out = native.normalize_images(img, mean, std)
    expected = (img.astype(np.float32) / 255.0 - np.asarray(mean, np.float32)) \
        / np.asarray(std, np.float32)
    np.testing.assert_allclose(out, expected, rtol=1e-5, atol=1e-6)


def test_synthetic_iterators_shapes():
    lm = iter(SyntheticLM(batch_size=4, seq_len=16, vocab_size=100))
    b = next(lm)
    assert b["inputs"].shape == (4, 17)
    assert b["inputs"].max() < 100
    img = iter(SyntheticImages(batch_size=2, image_size=8, num_classes=5))
    b = next(img)
    assert b["inputs"].shape == (2, 8, 8, 3)


def test_device_feeder_finite_iterator_raises_stopiteration():
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tf_operator_tpu.parallel.mesh import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(dp=-1))
    sharding = {"inputs": NamedSharding(mesh, P())}
    batches = [{"inputs": np.ones((2, 2), np.float32)} for _ in range(3)]
    feeder = DeviceFeeder(iter(batches), sharding)
    out = list(feeder)
    assert len(out) == 3
    feeder.stop()
