"""Metrics registry, exposition endpoint, structured logging, leader
election, and the CLI server assembly.

Reference behaviors covered: promauto counter catalog (docs/monitoring/
README.md), /metrics endpoint (main.go:39-50), logrus JSON + contextual
fields (util/logger.go), leaderelection.RunOrDie semantics
(app/server.go:146-193), signal/flag surface (options.go:53-83).
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.request

import pytest

from tf_operator_tpu.api.types import TPUJob
from tf_operator_tpu.runtime import metrics as m
from tf_operator_tpu.runtime.leaderelection import LEASES, LeaderElector
from tf_operator_tpu.runtime.logconfig import JSONFormatter, logger_for_job
from tf_operator_tpu.runtime.metrics import Registry
from tf_operator_tpu.runtime.monitoring import MonitoringServer
from tf_operator_tpu.runtime.store import Store


# --- registry ------------------------------------------------------------

def test_counter_inc_and_labels():
    r = Registry()
    c = r.counter("test_total", "help", ["ns"])
    c.inc(ns="a")
    c.inc(2, ns="a")
    c.inc(ns="b")
    assert c.value(ns="a") == 3
    assert c.value(ns="b") == 1
    assert c.value(ns="missing") == 0


def test_counter_label_mismatch_raises():
    r = Registry()
    c = r.counter("test_total", "help", ["ns"])
    with pytest.raises(ValueError):
        c.inc(wrong="x")


def test_gauge_set_inc_dec():
    r = Registry()
    g = r.gauge("test_gauge", "help")
    g.set(5)
    g.inc()
    g.dec(2)
    assert g.value() == 4


def test_registry_reregistration_returns_same_metric():
    r = Registry()
    a = r.counter("dup_total", "help", ["ns"])
    b = r.counter("dup_total", "help", ["ns"])
    assert a is b


def test_render_text_prometheus_format():
    r = Registry()
    c = r.counter("jobs_total", "Jobs seen", ["job_namespace"])
    c.inc(job_namespace="default")
    g = r.gauge("leader", "Leader flag")
    g.set(1)
    text = r.render_text()
    assert "# HELP jobs_total Jobs seen" in text
    assert "# TYPE jobs_total counter" in text
    assert 'jobs_total{job_namespace="default"} 1' in text
    assert "# TYPE leader gauge" in text
    assert "leader 1" in text


def test_render_escapes_label_values():
    r = Registry()
    c = r.counter("esc_total", "h", ["v"])
    c.inc(v='a"b\nc')
    assert 'esc_total{v="a\\"b\\nc"} 1' in r.render_text()


def test_histogram_buckets_and_sum():
    r = Registry()
    h = r.histogram("lat", "h", buckets=(0.1, 1.0, 10.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(100.0)
    text = r.render_text()
    assert 'lat_bucket{le="0.1"} 1' in text
    assert 'lat_bucket{le="1"} 2' in text
    assert 'lat_bucket{le="+Inf"} 3' in text
    assert "lat_count 3" in text


def test_histogram_timer():
    r = Registry()
    h = r.histogram("dur", "h", buckets=(10.0,))
    with h.time():
        pass
    assert "dur_count 1" in r.render_text()


# --- Histogram.quantile (serving SLO artifacts read p50/p99 locally) ----

def test_quantile_empty_series_is_none():
    h = Registry().histogram("q", "h", buckets=(1.0, 2.0))
    assert h.quantile(0.5) is None


def test_quantile_uniform_distribution_interpolates():
    # 100 observations spread uniformly over (0, 10]: every decile of
    # the data lands in a known bucket, and linear interpolation inside
    # the bucket recovers the value to within one observation's width.
    h = Registry().histogram("q", "h",
                             buckets=(2.0, 4.0, 6.0, 8.0, 10.0))
    for i in range(100):
        h.observe((i + 1) * 0.1)  # 0.1 .. 10.0
    assert h.quantile(0.0) == pytest.approx(0.0, abs=0.11)
    assert h.quantile(0.5) == pytest.approx(5.0, abs=0.11)
    assert h.quantile(0.9) == pytest.approx(9.0, abs=0.11)
    assert h.quantile(1.0) == pytest.approx(10.0, abs=1e-9)


def test_quantile_known_two_bucket_split():
    # 3 obs <= 1.0, 1 obs in (1.0, 3.0]: p50 = rank 2 of 4 -> 2/3 into
    # the first bucket; p99 = rank 3.96 -> 0.96 into the second.
    h = Registry().histogram("q", "h", buckets=(1.0, 3.0))
    for v in (0.2, 0.4, 0.9, 2.0):
        h.observe(v)
    assert h.quantile(0.5) == pytest.approx(2 / 3)
    assert h.quantile(0.99) == pytest.approx(1.0 + 0.96 * 2.0)


def test_quantile_overflow_bucket_clamps_to_highest_bound():
    # Prometheus histogram_quantile convention: ranks in +Inf clamp to
    # the highest finite bound — the histogram cannot resolve beyond it.
    h = Registry().histogram("q", "h", buckets=(1.0, 5.0))
    h.observe(0.5)
    h.observe(100.0)
    h.observe(200.0)
    assert h.quantile(0.99) == 5.0
    assert h.quantile(0.2) == pytest.approx(0.6)


def test_quantile_labeled_series_are_independent():
    r = Registry()
    h = r.histogram("q", "h", ["t"], buckets=(1.0, 10.0))
    h.observe(0.5, t="a")
    h.observe(9.0, t="b")
    assert h.quantile(1.0, t="a") <= 1.0
    assert h.quantile(1.0, t="b") > 1.0
    with pytest.raises(ValueError):
        h.quantile(1.5, t="a")


# --- monitoring endpoint -------------------------------------------------

@pytest.fixture()
def server():
    r = Registry()
    r.counter("up_total", "h").inc()
    s = MonitoringServer(port=0, registry=r)
    s.start()
    yield s
    s.stop()


def _get(server, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}{path}", timeout=5) as resp:
        return resp.status, resp.read().decode()


def test_metrics_endpoint(server):
    status, body = _get(server, "/metrics")
    assert status == 200
    assert "up_total 1" in body


def test_healthz_and_version(server):
    assert _get(server, "/healthz")[0] == 200
    status, body = _get(server, "/version")
    assert status == 200
    assert "tpu-operator" in json.loads(body)["version"]


def test_debug_stacks(server):
    status, body = _get(server, "/debug/stacks")
    assert status == 200
    assert "thread" in body


def test_unknown_path_404(server):
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(server, "/nope")
    assert ei.value.code == 404


# --- flight-recorder endpoints (runtime/trace.py; docs/observability.md) --

@pytest.fixture()
def trace_state():
    from tf_operator_tpu.runtime import trace

    trace.reset_for_tests()
    yield trace
    trace.reset_for_tests()


def _get_raw(server, path):
    req = urllib.request.urlopen(
        f"http://127.0.0.1:{server.port}{path}", timeout=5)
    with req as resp:
        return resp.status, resp.headers.get("Content-Type"), \
            resp.read().decode()


def test_debug_traces_empty_recorder_shape(server, trace_state):
    """Tracing off: /debug/traces stays served — enabled false, no
    traces, zero seen — with a JSON content type."""
    status, ctype, body = _get_raw(server, "/debug/traces")
    assert status == 200
    assert ctype == "application/json"
    payload = json.loads(body)
    assert payload["enabled"] is False
    assert payload["traces"] == []
    assert payload["traces_seen"] == 0
    assert payload["retained"] == {"slowest": 0, "errored": 0,
                                   "sampled": 0}
    assert payload["phase_totals_s"] == {}


def test_debug_traces_serves_slow_sync_retention(server, trace_state):
    """A deliberately slow sync is retained by the slowest-N policy and
    visible over HTTP with its child spans."""
    import time as _time

    trace_state.configure(True)
    with trace_state.span("sync", job="default/slow"):
        with trace_state.span("pods.list"):
            _time.sleep(0.02)
    for _ in range(5):
        with trace_state.span("sync", job="default/fast"):
            pass
    _, _, body = _get_raw(server, "/debug/traces")
    payload = json.loads(body)
    assert payload["enabled"] is True
    assert payload["traces_seen"] == 6
    slowest = payload["traces"][0]
    assert slowest["spans"][-1]["attrs"]["job"] == "default/slow"
    assert {s["name"] for s in slowest["spans"]} == {"sync", "pods.list"}
    assert slowest["duration_ms"] >= 20


def test_debug_jobs_unknown_job_404s(server, trace_state):
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(server, "/debug/jobs/default/ghost")
    assert ei.value.code == 404
    assert "decision journal" in json.loads(ei.value.read().decode())[
        "error"]
    # Malformed paths 404 too (no namespace/name split).
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(server, "/debug/jobs/onlyns")
    assert ei.value.code == 404


def test_debug_jobs_serves_decision_journal_shape(server, trace_state):
    trace_state.JOURNAL.record("default", "j1", "admission.defer",
                               "capacity", "needs 8 chips; 4/4 in use")
    trace_state.JOURNAL.record("default", "j1", "admission.admit",
                               "admitted", "8 chips")
    status, ctype, body = _get_raw(server, "/debug/jobs/default/j1")
    assert status == 200
    assert ctype == "application/json"
    payload = json.loads(body)
    assert payload["namespace"] == "default"
    assert payload["name"] == "j1"
    assert [d["kind"] for d in payload["decisions"]] == [
        "admission.defer", "admission.admit"]
    for d in payload["decisions"]:
        assert {"seq", "time", "last_time", "kind", "reason", "message",
                "trace_id", "span", "count"} <= set(d)


def test_tracing_off_is_shared_noop_and_records_nothing(trace_state):
    """The zero-overhead contract: disabled, span() allocates nothing
    (it returns the one shared no-op object) and a full sync leaves the
    recorder untouched."""
    from tf_operator_tpu.controller.tpu_controller import TPUJobController
    from tf_operator_tpu.runtime import store as store_mod
    from tf_operator_tpu.testutil import new_tpujob

    assert trace_state.span("sync") is trace_state.span("pods.list") \
        is trace_state.NOOP_SPAN
    store = Store()
    controller = TPUJobController(store)
    job = new_tpujob(worker=1, name="untraced")
    store.create(store_mod.TPUJOBS, job)
    controller.sync_tpujob("default/untraced")
    assert trace_state.RECORDER.snapshot()["traces_seen"] == 0
    assert trace_state.RECORDER.phase_totals() == {}
    store.stop_watchers()


# --- metric cardinality: job-labeled series pruned by job GC --------------

def test_metric_remove_drops_child_series():
    r = Registry()
    g = r.gauge("job_gauge", "h", ["job_namespace", "job"])
    g.set(0.5, job_namespace="ns", job="a")
    g.set(0.9, job_namespace="ns", job="b")
    g.remove(job_namespace="ns", job="a")
    g.remove(job_namespace="ns", job="never-existed")  # no-op
    text = r.render_text()
    assert 'job="a"' not in text
    assert 'job="b"' in text
    h = r.histogram("job_hist", "h", ["job"], buckets=(1.0,))
    h.observe(0.5, job="a")
    h.remove(job="a")
    assert 'job="a"' not in r.render_text()


def test_job_gc_prunes_job_labeled_series_and_journal(trace_state):
    """Create -> delete a job through the controller's watch path; its
    goodput/slices series must leave render_text() and its decision
    journal must forget it (unbounded cardinality fix)."""
    import time as _time

    from tf_operator_tpu.controller.tpu_controller import TPUJobController
    from tf_operator_tpu.runtime import metrics as mx
    from tf_operator_tpu.runtime import store as store_mod
    from tf_operator_tpu.runtime.metrics import REGISTRY
    from tf_operator_tpu.testutil import new_tpujob

    store = Store()
    controller = TPUJobController(store)
    controller.start_watching()
    try:
        job = new_tpujob(worker=1, name="gc-job")
        store.create(store_mod.TPUJOBS, job)
        mx.job_goodput_ratio.set(0.75, job_namespace="default",
                                 job="gc-job")
        mx.job_slices.set(2, job_namespace="default", job="gc-job")
        trace_state.JOURNAL.record("default", "gc-job",
                                   "admission.admit", "admitted", "m")
        assert 'job="gc-job"' in REGISTRY.render_text()
        store.delete(store_mod.TPUJOBS, "default", "gc-job")
        deadline = _time.monotonic() + 5
        while _time.monotonic() < deadline:
            if ('job="gc-job"' not in REGISTRY.render_text()
                    and trace_state.JOURNAL.decisions(
                        "default", "gc-job") is None):
                break
            _time.sleep(0.01)
        assert 'job="gc-job"' not in REGISTRY.render_text()
        assert trace_state.JOURNAL.decisions("default", "gc-job") is None
    finally:
        controller.stop()
        store.stop_watchers()


# --- structured logging --------------------------------------------------

def test_json_formatter_fields():
    rec = logging.LogRecord("tpu_operator.test", logging.INFO, "f.py", 10,
                            "hello %s", ("world",), None)
    out = json.loads(JSONFormatter().format(rec))
    assert out["msg"] == "hello world"
    assert out["level"] == "info"
    assert out["filename"].startswith("f.py:")


def test_logger_for_job_attaches_context(caplog):
    job = TPUJob()
    job.metadata.name = "j1"
    job.metadata.namespace = "ns1"
    job.metadata.uid = "u-1"
    base = logging.getLogger("tpu_operator.testctx")
    adapter = logger_for_job(base, job, rtype="worker", index=3)
    with caplog.at_level(logging.INFO, logger="tpu_operator.testctx"):
        adapter.info("msg")
    rec = caplog.records[-1]
    out = json.loads(JSONFormatter().format(rec))
    assert out["job"] == "ns1.j1"
    assert out["replica_type"] == "worker"
    assert out["replica_index"] == 3


# --- leader election -----------------------------------------------------

def _elector(store, ident, **kw):
    kw.setdefault("lease_duration", 0.5)
    kw.setdefault("renew_deadline", 0.2)
    kw.setdefault("retry_period", 0.05)
    return LeaderElector(store, identity=ident, **kw)


def test_single_elector_acquires():
    store = Store()
    e = _elector(store, "a")
    e.start()
    assert e.wait_until_leading(timeout=5)
    assert m.is_leader.value() == 1
    e.stop()
    assert m.is_leader.value() == 0


def test_second_elector_blocked_until_release():
    store = Store()
    a = _elector(store, "a")
    a.start()
    assert a.wait_until_leading(timeout=5)
    b = _elector(store, "b")
    b.start()
    assert not b.wait_until_leading(timeout=0.3)
    a.stop()  # releases the lease
    assert b.wait_until_leading(timeout=5)
    b.stop()


def test_takeover_after_holder_expires():
    store = Store()
    a = _elector(store, "a")
    a.start()
    assert a.wait_until_leading(timeout=5)
    # Simulate a crashed holder: kill the thread without release.
    a._stop.set()
    a._thread.join(timeout=2)
    b = _elector(store, "b")
    b.start()
    assert b.wait_until_leading(timeout=5)  # takes over after expiry
    lease = store.get(LEASES, "default", "tpu-operator")
    assert lease.spec.holder_identity == "b"
    assert lease.spec.lease_transitions >= 1
    b.stop()


def test_on_started_leading_callback():
    store = Store()
    started = threading.Event()
    e = _elector(store, "a", on_started_leading=started.set)
    e.start()
    assert started.wait(timeout=5)
    e.stop()


def test_lost_lease_fires_on_stopped_leading():
    store = Store()
    stopped = threading.Event()
    a = _elector(store, "a", on_stopped_leading=stopped.set)
    a.start()
    assert a.wait_until_leading(timeout=5)
    # Usurp the lease out from under the holder.
    lease = store.get(LEASES, "default", "tpu-operator")
    lease.spec.holder_identity = "usurper"
    import datetime as dt
    lease.spec.renew_time = (dt.datetime.now(dt.timezone.utc)
                             + dt.timedelta(seconds=60))
    store.update(LEASES, lease)
    assert stopped.wait(timeout=5)
    a._stop.set()


# --- CLI server assembly -------------------------------------------------

def test_cli_version(capsys):
    from tf_operator_tpu.cli import main
    assert main(["--version"]) == 0
    assert "tpu-operator" in capsys.readouterr().out


def test_cli_server_end_to_end(tmp_path):
    """Full process assembly: leader election -> controller -> a job runs
    to completion; metrics visible over HTTP."""
    import sys

    from tf_operator_tpu.cli import Server, build_parser
    from tf_operator_tpu.sdk.client import TPUJobClient
    from tf_operator_tpu.testutil import new_tpujob

    args = build_parser().parse_args(
        ["--monitoring-port", "-1", "--threadiness", "1",
         "--resync-period", "0.2"])
    server = Server(args)
    try:
        server.start()
        assert server.elector is not None
        assert server.elector.wait_until_leading(timeout=10)

        client = TPUJobClient(server.store)
        job = new_tpujob(worker=1, name="cli-e2e",
                         command=[sys.executable, "-c", "pass"])
        client.create(job)
        client.wait_for_job("cli-e2e", timeout=30)

        status, body = _get(server.monitoring, "/metrics")
        assert status == 200
        assert 'tpu_operator_jobs_successful_total{job_namespace="default"}' \
            in body
        assert "tpu_operator_is_leader 1" in body
    finally:
        server.shutdown()


def test_event_store_mirror_capped():
    """Persisted events are labeled with their job name and the
    collection is pruned once it exceeds the cap (no unbounded growth
    on a long-running operator)."""
    from tf_operator_tpu import operator as op_mod
    from tf_operator_tpu.api import constants
    from tf_operator_tpu.api.types import ObjectMeta, Pod
    from tf_operator_tpu.operator import Operator
    from tf_operator_tpu.runtime import store as store_mod

    op = Operator(backend=None)
    pod = Pod(metadata=ObjectMeta(
        name="capjob-worker-0",
        labels={constants.LABEL_JOB_NAME: "capjob"}))
    for _ in range(op_mod.MAX_STORED_EVENTS + 10):
        op.recorder.event(pod, "Normal", "Probe", "x")
    count = op.store.count(store_mod.EVENTS)
    assert count <= op_mod.MAX_STORED_EVENTS, count
    ev = op.store.list(store_mod.EVENTS)[0]
    assert ev.metadata.labels[constants.LABEL_JOB_NAME] == "capjob"

# --- event aggregation (EventCorrelator analog, ISSUE 2) -----------------

def _named(name="storm-job", ns="default"):
    from tf_operator_tpu.api.types import ObjectMeta, TPUJob

    return TPUJob(metadata=ObjectMeta(name=name, namespace=ns))


def test_exact_duplicate_events_fold_into_count():
    from tf_operator_tpu.runtime.events import Recorder

    sunk = []
    r = Recorder(sink=sunk.append)
    for _ in range(5):
        r.event(_named(), "Warning", "AbnormalPod", "same message")
    evs = r.events_for(reason="AbnormalPod")
    assert len(evs) == 1
    assert evs[0].count == 5
    assert len(sunk) == 1, "duplicates must not re-fan-out to the sink"


def test_similar_event_storm_collapses_past_threshold():
    """>threshold distinct-message events with the same (object, type,
    reason) collapse into one combined record — a 256-pod gang start is
    ~11 sink calls, not 256 API writes."""
    from tf_operator_tpu.runtime.events import (
        SIMILAR_EVENTS_THRESHOLD,
        Recorder,
    )

    sunk = []
    r = Recorder(sink=sunk.append)
    for i in range(256):
        r.event(_named(), "Normal", "SuccessfulCreatePod",
                f"Created pod: w-{i}")
    evs = r.events_for(reason="SuccessfulCreatePod")
    assert len(evs) == SIMILAR_EVENTS_THRESHOLD + 1
    assert len(sunk) == SIMILAR_EVENTS_THRESHOLD
    combined = [e for e in evs
                if e.message.startswith("(combined from similar events)")]
    assert len(combined) == 1
    assert combined[0].count == 256


def test_distinct_reasons_do_not_aggregate():
    from tf_operator_tpu.runtime.events import Recorder

    r = Recorder()
    r.event(_named(), "Normal", "ReasonA", "m")
    r.event(_named(), "Normal", "ReasonB", "m")
    assert len(r.events) == 2


def test_aggregated_events_counted_in_metric():
    from tf_operator_tpu.runtime import metrics as mx
    from tf_operator_tpu.runtime.events import Recorder

    before = mx.events_aggregated.value()
    r = Recorder()
    for _ in range(4):
        r.event(_named("metric-job"), "Normal", "Dup", "m")
    assert mx.events_aggregated.value() == before + 3


# --- workqueue instrumentation (gauge owned by the queue, ISSUE 2) --------

def test_workqueue_owns_depth_gauge_and_counts_coalesced():
    from tf_operator_tpu.runtime import metrics as mx
    from tf_operator_tpu.runtime.workqueue import RateLimitingQueue

    q = RateLimitingQueue()
    coalesced_before = mx.workqueue_coalesced.value()
    q.add("k1")
    q.add("k2")
    assert mx.workqueue_depth.value() == 2
    q.add("k1")  # already pending: coalesced, depth unchanged
    assert mx.workqueue_depth.value() == 2
    assert mx.workqueue_coalesced.value() == coalesced_before + 1
    assert q.get(timeout=1) == "k1"
    assert mx.workqueue_depth.value() == 1
    q.done("k1")
    q.shutdown()


def test_workqueue_latency_histogram_observes_wait():
    import time as _time

    from tf_operator_tpu.runtime import metrics as mx
    from tf_operator_tpu.runtime.workqueue import RateLimitingQueue

    count_before = sum(mx.workqueue_latency_seconds._totals.values())
    q = RateLimitingQueue()
    q.add("k")
    _time.sleep(0.01)
    q.get(timeout=1)
    q.done("k")
    q.shutdown()
    assert sum(mx.workqueue_latency_seconds._totals.values()) \
        == count_before + 1


# CI shard (pyproject [tool.pytest.ini_options] markers)
pytestmark = pytest.mark.control_plane
