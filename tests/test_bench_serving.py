"""bench_serving.py smoke: the harness runs at a tiny shape under
tier-1 and the one-JSON-line artifact schema stays pinned (bench.py
conventions — same reasoning as tests/test_bench_controlplane.py)."""

import json
import subprocess
import sys
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "benchmarks", "bench_serving.py")


@pytest.fixture(scope="module")
def artifact():
    proc = subprocess.run(
        [sys.executable, BENCH, "--requests", "60", "--qps", "5000",
         "--slots", "4", "--tenants", "2", "--seed", "7"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, f"exactly one JSON line, got: {proc.stdout!r}"
    return json.loads(lines[0])


def test_artifact_schema(artifact):
    for key in ("metric", "value", "unit", "qps", "ttft_p50_s",
                "ttft_p99_s", "queue_depth_max", "requests", "completed",
                "rejected", "elapsed_s", "env", "config_fingerprint"):
        assert key in artifact, f"missing {key}"
    assert artifact["metric"] == "serving_tokens_per_sec[fake]"
    assert artifact["unit"] == "tokens/sec"
    assert isinstance(artifact["config_fingerprint"], str)
    assert len(artifact["config_fingerprint"]) == 12


def test_throughput_and_completion(artifact):
    assert artifact["value"] > 0
    assert artifact["completed"] + artifact["rejected"] == 60
    assert artifact["completed"] > 0


def test_ttft_quantiles_ordered(artifact):
    # p99 >= p50 by construction of Histogram.quantile; both present
    # when any request completed.
    assert artifact["ttft_p50_s"] is not None
    assert artifact["ttft_p99_s"] >= artifact["ttft_p50_s"]


def test_fingerprint_tracks_config():
    proc = subprocess.run(
        [sys.executable, BENCH, "--requests", "20", "--qps", "5000",
         "--slots", "2", "--seed", "1"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    other = json.loads(proc.stdout.strip())
    # Different shape -> different fingerprint: artifacts from distinct
    # configs can never be median-compared by accident.
    base = subprocess.run(
        [sys.executable, BENCH, "--requests", "20", "--qps", "5000",
         "--slots", "4", "--seed", "1"],
        capture_output=True, text=True, timeout=120)
    assert other["config_fingerprint"] != json.loads(
        base.stdout.strip())["config_fingerprint"]


# CI shard (pyproject [tool.pytest.ini_options] markers)
pytestmark = pytest.mark.control_plane
