"""bench_serving.py smoke: the harness runs at a tiny shape under
tier-1 and the one-JSON-line artifact schema stays pinned (bench.py
conventions — same reasoning as tests/test_bench_controlplane.py)."""

import json
import subprocess
import sys
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "benchmarks", "bench_serving.py")


@pytest.fixture(scope="module")
def artifact():
    proc = subprocess.run(
        [sys.executable, BENCH, "--requests", "60", "--qps", "5000",
         "--slots", "4", "--tenants", "2", "--seed", "7"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, f"exactly one JSON line, got: {proc.stdout!r}"
    return json.loads(lines[0])


def test_artifact_schema(artifact):
    for key in ("metric", "value", "unit", "qps", "ttft_p50_s",
                "ttft_p99_s", "queue_depth_max", "requests", "completed",
                "rejected", "elapsed_s", "env", "config_fingerprint"):
        assert key in artifact, f"missing {key}"
    assert artifact["metric"] == "serving_tokens_per_sec[fake]"
    assert artifact["unit"] == "tokens/sec"
    assert isinstance(artifact["config_fingerprint"], str)
    assert len(artifact["config_fingerprint"]) == 12


def test_throughput_and_completion(artifact):
    assert artifact["value"] > 0
    assert artifact["completed"] + artifact["rejected"] == 60
    assert artifact["completed"] > 0


def test_ttft_quantiles_ordered(artifact):
    # p99 >= p50 by construction of Histogram.quantile; both present
    # when any request completed.
    assert artifact["ttft_p50_s"] is not None
    assert artifact["ttft_p99_s"] >= artifact["ttft_p50_s"]


@pytest.fixture(scope="module")
def diurnal_artifact():
    """One tiny diurnal run (ISSUE 18 acceptance scenario) — a single
    short period, fast settle/cooldown, so tier-1 stays quick. The
    headline savings number is only meaningful at the default shape
    (benchmarks/bench_serving.py docstring); here we pin the SCHEMA and
    the zero-drop invariant."""
    proc = subprocess.run(
        [sys.executable, BENCH, "--scenario", "diurnal",
         "--period", "0.8", "--periods", "1", "--peak-qps", "30",
         "--trough-qps", "5", "--per-slice-rate", "25",
         "--settle-seconds", "0.05", "--cooldown", "0.1",
         "--autoscale-interval", "0.03", "--seed", "7"],
        capture_output=True, text=True, timeout=180)
    assert proc.returncode == 0, proc.stderr
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, f"exactly one JSON line, got: {proc.stdout!r}"
    return json.loads(lines[0])


def test_diurnal_artifact_schema(diurnal_artifact):
    for key in ("metric", "value", "unit", "slo_s", "slo_met",
                "autoscale", "static", "env", "config_fingerprint"):
        assert key in diurnal_artifact, f"missing {key}"
    assert diurnal_artifact["metric"] == "serving_diurnal_chip_seconds_saved"
    assert diurnal_artifact["unit"] == "percent"
    assert isinstance(diurnal_artifact["slo_met"], bool)
    for run in ("autoscale", "static"):
        for key in ("submitted", "completed", "rejected_429", "dropped",
                    "chip_seconds", "slices_peak", "slices_max_seen",
                    "ttft_p99_s", "resizes_grow", "resizes_shrink",
                    "elapsed_s"):
            assert key in diurnal_artifact[run], f"missing {run}.{key}"


def test_diurnal_zero_drops_and_real_traffic(diurnal_artifact):
    """The acceptance invariant that holds at ANY shape: nothing
    admitted by the gateway is ever lost — every submitted request is
    either streamed to completion or rejected up front with a 429."""
    for run in ("autoscale", "static"):
        r = diurnal_artifact[run]
        assert r["dropped"] == 0
        assert r["completed"] > 0
        assert r["completed"] + r["rejected_429"] == r["submitted"]
    # The static fleet holds peak size throughout; the autoscaled fleet
    # can never exceed it.
    auto, static = diurnal_artifact["autoscale"], diurnal_artifact["static"]
    assert auto["slices_max_seen"] <= static["slices_peak"]
    assert static["chip_seconds"] > 0


def test_fingerprint_tracks_config():
    proc = subprocess.run(
        [sys.executable, BENCH, "--requests", "20", "--qps", "5000",
         "--slots", "2", "--seed", "1"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    other = json.loads(proc.stdout.strip())
    # Different shape -> different fingerprint: artifacts from distinct
    # configs can never be median-compared by accident.
    base = subprocess.run(
        [sys.executable, BENCH, "--requests", "20", "--qps", "5000",
         "--slots", "4", "--seed", "1"],
        capture_output=True, text=True, timeout=120)
    assert other["config_fingerprint"] != json.loads(
        base.stdout.strip())["config_fingerprint"]


# CI shard (pyproject [tool.pytest.ini_options] markers)
pytestmark = pytest.mark.control_plane
