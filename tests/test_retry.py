"""runtime/retry.py + runtime/chaos.py unit coverage: transient
classification, backoff/jitter/deadline behavior, conflict-aware
read-modify-write, degraded-mode entry/exit and its disruption gates,
the seeded fault injector, the ChaosStore fault surface, and the HTTP
fake's FaultProfile path.
"""

import threading
import time

import pytest

from tf_operator_tpu.api.types import ObjectMeta, Pod
from tf_operator_tpu.runtime import metrics, store as store_mod
from tf_operator_tpu.runtime.chaos import (
    ChaosStore,
    FaultInjector,
    FaultProfile,
)
from tf_operator_tpu.runtime.retry import (
    ControlPlaneHealth,
    RetryPolicy,
    TransientAPIError,
    is_transient,
    update_with_conflict_retry,
    with_retries,
)
from tf_operator_tpu.runtime.store import Store


# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------

def test_semantic_outcomes_are_not_transient():
    assert not is_transient(store_mod.NotFoundError("x"))
    assert not is_transient(store_mod.ConflictError("x"))
    assert not is_transient(store_mod.AlreadyExistsError("x"))
    assert not is_transient(ValueError("x"))


def test_infrastructure_blips_are_transient():
    assert is_transient(TransientAPIError("boom"))
    assert is_transient(TimeoutError("slow"))
    assert is_transient(ConnectionResetError("gone"))
    assert is_transient(OSError("net"))


def test_status_code_classification():
    assert is_transient(TransientAPIError("t", code=503))
    assert is_transient(TransientAPIError("t", code=429))
    assert not is_transient(TransientAPIError("t", code=400))


# ---------------------------------------------------------------------------
# with_retries
# ---------------------------------------------------------------------------

def test_retries_then_succeeds():
    calls = [0]

    def flaky():
        calls[0] += 1
        if calls[0] < 3:
            raise TransientAPIError("blip")
        return "ok"

    assert with_retries(flaky, sleep=lambda s: None) == "ok"
    assert calls[0] == 3


def test_exhausted_retries_reraise_last_error():
    policy = RetryPolicy(max_attempts=3)
    calls = [0]

    def always():
        calls[0] += 1
        raise TransientAPIError("persistent")

    with pytest.raises(TransientAPIError):
        with_retries(always, policy=policy, sleep=lambda s: None)
    assert calls[0] == 3


def test_non_retryable_raises_immediately():
    calls = [0]

    def conflict():
        calls[0] += 1
        raise store_mod.ConflictError("cas")

    with pytest.raises(store_mod.ConflictError):
        with_retries(conflict, sleep=lambda s: None)
    assert calls[0] == 1


def test_backoff_is_capped_exponential_with_full_jitter():
    policy = RetryPolicy(base_delay=0.1, max_delay=0.4, max_attempts=5)
    # rng=1.0 -> the delay IS the cap for that attempt.
    delays = [policy.delay(a, lambda: 1.0) for a in range(4)]
    assert delays == [0.1, 0.2, 0.4, 0.4]
    # full jitter: rng=0 -> zero delay.
    assert policy.delay(3, lambda: 0.0) == 0.0


def test_deadline_stops_retrying():
    policy = RetryPolicy(base_delay=10.0, max_delay=10.0,
                         max_attempts=10, deadline_seconds=0.01)
    calls = [0]

    def always():
        calls[0] += 1
        raise TransientAPIError("blip")

    with pytest.raises(TransientAPIError):
        with_retries(always, policy=policy, sleep=lambda s: None,
                     rng=lambda: 1.0)
    # The first backoff (10s) already overshoots the 10ms deadline.
    assert calls[0] == 1


def test_retries_counted_in_metric():
    before = metrics.api_retries.value(component="test.retry")
    calls = [0]

    def flaky():
        calls[0] += 1
        if calls[0] < 2:
            raise TransientAPIError("blip")

    with_retries(flaky, component="test.retry", sleep=lambda s: None)
    assert metrics.api_retries.value(component="test.retry") == before + 1


# ---------------------------------------------------------------------------
# conflict-aware read-modify-write
# ---------------------------------------------------------------------------

def _pod(name="p", ns="default"):
    p = Pod(metadata=ObjectMeta(name=name, namespace=ns))
    return p


def test_conflict_retry_reapplies_on_fresh_state():
    store = Store()
    store.create(store_mod.PODS, _pod())

    raced = [False]

    class RacingStore:
        """First update loses to a concurrent writer; the retry must
        re-read and land the mutation on the NEW version."""

        def try_get(self, kind, ns, name):
            return store.try_get(kind, ns, name)

        def update(self, kind, obj):
            if not raced[0]:
                raced[0] = True
                fresh = store.get(kind, obj.metadata.namespace,
                                  obj.metadata.name)
                fresh.metadata.labels["racer"] = "won"
                store.update(kind, fresh)
                raise store_mod.ConflictError("lost the race")
            return store.update(kind, obj)

        def update_status(self, kind, obj):
            return store.update_status(kind, obj)

    def mutate(cur):
        cur.metadata.annotations["stamped"] = "yes"

    out = update_with_conflict_retry(RacingStore(), store_mod.PODS,
                                     "default", "p", mutate)
    assert out is not None
    final = store.get(store_mod.PODS, "default", "p")
    # Both the racer's write and ours survived — nothing clobbered.
    assert final.metadata.annotations["stamped"] == "yes"
    assert final.metadata.labels["racer"] == "won"


def test_conflict_retry_aborts_when_precondition_fails():
    store = Store()
    store.create(store_mod.PODS, _pod())
    out = update_with_conflict_retry(store, store_mod.PODS, "default",
                                     "p", lambda cur: False)
    assert out is None


def test_conflict_retry_none_on_vanished_object():
    store = Store()
    out = update_with_conflict_retry(store, store_mod.PODS, "default",
                                     "ghost", lambda cur: None)
    assert out is None


# ---------------------------------------------------------------------------
# degraded mode
# ---------------------------------------------------------------------------

def _health(threshold=0.0, failures=3):
    clock = [0.0]
    h = ControlPlaneHealth(threshold_seconds=threshold,
                           failure_threshold=failures,
                           clock=lambda: clock[0])
    return h, clock


def test_degraded_needs_both_streak_and_duration():
    h, clock = _health(threshold=5.0, failures=3)
    for _ in range(10):
        h.record_failure()
    assert not h.degraded  # streak yes, duration no
    clock[0] = 6.0
    h.record_failure()
    assert h.degraded


def test_single_blip_never_degrades():
    h, clock = _health(threshold=0.0, failures=5)
    for _ in range(4):
        h.record_failure()
    assert not h.degraded
    h.record_success()
    for _ in range(4):
        h.record_failure()
    assert not h.degraded  # success reset the streak


def test_success_clears_degraded_and_gauge():
    h, clock = _health(threshold=0.0, failures=2)
    h.record_failure()
    h.record_failure()
    assert h.degraded
    assert metrics.controlplane_degraded.value() == 1
    assert not h.allow_disruption("drain")
    h.record_success()
    assert not h.degraded
    assert metrics.controlplane_degraded.value() == 0
    assert h.allow_disruption("drain")


def test_deferred_disruptions_counted():
    h, clock = _health(threshold=0.0, failures=1)
    h.record_failure()
    before = metrics.disruptions_deferred.value(action="test-action")
    assert not h.allow_disruption("test-action")
    assert not h.allow_disruption("test-action")
    assert metrics.disruptions_deferred.value(
        action="test-action") == before + 2
    h.record_success()


def test_with_retries_feeds_health():
    h, clock = _health(threshold=0.0, failures=2)
    policy = RetryPolicy(max_attempts=2)

    def always():
        raise TransientAPIError("down")

    with pytest.raises(TransientAPIError):
        with_retries(always, policy=policy, health=h,
                     sleep=lambda s: None)
    assert h.degraded  # 2 attempts = 2 recorded failures
    with_retries(lambda: "ok", health=h)
    assert not h.degraded


# ---------------------------------------------------------------------------
# FaultProfile / FaultInjector
# ---------------------------------------------------------------------------

def test_named_profiles():
    off = FaultProfile.named("off")
    assert off.write_error_rate == 0.0
    default = FaultProfile.named("default", seed=3)
    assert default.write_error_rate >= 0.05
    assert default.conflict_rate >= 0.05
    assert default.seed == 3
    with pytest.raises(ValueError):
        FaultProfile.named("nope")


def test_overrides_win_most_specific_first():
    p = FaultProfile(write_error_rate=0.5, overrides={
        ("create", "pods"): {"write_error": 0.0},
        ("*", "nodes"): {"write_error": 1.0},
    })
    assert p.rate("write_error", "create", "pods") == 0.0
    assert p.rate("write_error", "delete", "nodes") == 1.0
    assert p.rate("write_error", "delete", "pods") == 0.5


def test_injector_is_seed_deterministic():
    a = FaultInjector(FaultProfile(seed=42, write_error_rate=0.3))
    b = FaultInjector(FaultProfile(seed=42, write_error_rate=0.3))
    seq_a = [a.decide("write_error") for _ in range(100)]
    seq_b = [b.decide("write_error") for _ in range(100)]
    assert seq_a == seq_b
    assert a.snapshot()["write_error"] == sum(seq_a)


# ---------------------------------------------------------------------------
# ChaosStore
# ---------------------------------------------------------------------------

def test_chaos_store_passthrough_with_zero_rates():
    base = Store()
    chaos = ChaosStore(base, FaultProfile())
    chaos.create(store_mod.PODS, _pod())
    assert chaos.get(store_mod.PODS, "default", "p").metadata.name == "p"
    assert len(chaos.list(store_mod.PODS)) == 1
    assert chaos.try_delete(store_mod.PODS, "default", "p")


def test_chaos_store_injects_write_errors():
    base = Store()
    chaos = ChaosStore(base, FaultProfile(seed=1, write_error_rate=1.0))
    with pytest.raises(TransientAPIError):
        chaos.create(store_mod.PODS, _pod())
    # Nothing landed: the fault fired before the write applied.
    assert base.count(store_mod.PODS) == 0


def test_chaos_store_injects_conflicts_on_updates_only():
    base = Store()
    base.create(store_mod.PODS, _pod())
    chaos = ChaosStore(base, FaultProfile(seed=1, conflict_rate=1.0))
    # create is conflict-free (conflicts are a CAS concept)...
    chaos.create(store_mod.PODS, _pod(name="other"))
    # ...updates always conflict under rate 1.0.
    cur = base.get(store_mod.PODS, "default", "p")
    with pytest.raises(store_mod.ConflictError):
        chaos.update(store_mod.PODS, cur)


def test_chaos_store_stale_read_serves_previous_version():
    base = Store()
    base.create(store_mod.PODS, _pod())
    chaos = ChaosStore(base, FaultProfile(seed=1, stale_read_rate=1.0))
    cur = base.get(store_mod.PODS, "default", "p")
    cur.metadata.labels["v"] = "2"
    chaos.update(store_mod.PODS, cur)  # stashes v1, applies v2
    stale = chaos.get(store_mod.PODS, "default", "p")
    assert "v" not in stale.metadata.labels  # served the OLD version
    assert base.get(store_mod.PODS, "default",
                    "p").metadata.labels["v"] == "2"


def test_chaos_store_lost_response_applies_then_raises():
    base = Store()
    chaos = ChaosStore(base, FaultProfile(seed=1,
                                          lost_response_rate=1.0))
    with pytest.raises(TransientAPIError):
        chaos.create(store_mod.PODS, _pod())
    # The write LANDED; only the reply was lost — the retry-idempotency
    # hazard production code must survive.
    assert base.count(store_mod.PODS) == 1


def test_chaos_store_drops_watch_events():
    base = Store()
    chaos = ChaosStore(base, FaultProfile(seed=1, watch_drop_rate=1.0))
    got = []
    w = chaos.watch(store_mod.PODS, lambda et, obj: got.append(et))
    base.create(store_mod.PODS, _pod())
    time.sleep(0.2)
    w.stop()
    assert got == []  # every event lost on the wire


def test_watch_handler_errors_counted_and_survived():
    base = Store()
    before = metrics.store_watch_handler_errors.value(
        kind=store_mod.PODS)
    fired = threading.Event()

    def bad_handler(et, obj):
        fired.set()
        raise RuntimeError("handler bug")

    w = base.watch(store_mod.PODS, bad_handler, replay=False)
    base.create(store_mod.PODS, _pod())
    base.create(store_mod.PODS, _pod(name="q"))
    assert fired.wait(2.0)
    deadline = time.monotonic() + 2.0
    while (metrics.store_watch_handler_errors.value(kind=store_mod.PODS)
           < before + 2 and time.monotonic() < deadline):
        time.sleep(0.01)
    w.stop()
    assert metrics.store_watch_handler_errors.value(
        kind=store_mod.PODS) >= before + 2
    assert w.error_count >= 2  # dispatcher survived both


# ---------------------------------------------------------------------------
# HTTP fake FaultProfile path (kube_fake)
# ---------------------------------------------------------------------------

def test_fake_apiserver_injects_profile_faults():
    import json
    import urllib.error
    import urllib.request

    from tf_operator_tpu.runtime.kube_fake import FakeKubeApiServer

    with FakeKubeApiServer(rbac_path=None) as srv:
        inj = srv.state.set_fault_profile(
            FaultProfile(seed=5, read_error_rate=1.0))
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(
                f"{srv.url}/api/v1/namespaces/default/pods", timeout=5)
        assert exc.value.code == 500
        assert inj.snapshot()["read_error"] == 1
        # Clearing the profile restores clean service.
        srv.state.set_fault_profile(None)
        with urllib.request.urlopen(
                f"{srv.url}/api/v1/namespaces/default/pods",
                timeout=5) as resp:
            assert json.loads(resp.read())["kind"] == "List"


def test_fake_apiserver_stale_reads_serve_history():
    import json
    import urllib.request

    from tf_operator_tpu.runtime.kube_fake import FakeKubeApiServer

    with FakeKubeApiServer(rbac_path=None) as srv:
        srv.state.set_fault_profile(
            FaultProfile(seed=5, stale_read_rate=1.0))
        srv.state.create("pods", "default", {
            "metadata": {"name": "p"}, "spec": {"containers": []}})
        srv.state.patch("pods", "default", "p",
                        {"metadata": {"labels": {"v": "2"}}})
        stale = srv.state.get("pods", "default", "p")
        assert "v" not in (stale["metadata"].get("labels") or {})


def test_fake_apiserver_injected_conflict_on_patch():
    import json
    import urllib.error
    import urllib.request

    from tf_operator_tpu.runtime.kube_fake import FakeKubeApiServer

    with FakeKubeApiServer(rbac_path=None) as srv:
        srv.state.create("pods", "default", {
            "metadata": {"name": "p"}, "spec": {"containers": []}})
        srv.state.set_fault_profile(
            FaultProfile(seed=5, conflict_rate=1.0))
        body = json.dumps({"metadata": {"labels": {"x": "1"}}}).encode()
        req = urllib.request.Request(
            f"{srv.url}/api/v1/namespaces/default/pods/p", data=body,
            method="PATCH",
            headers={"Content-Type": "application/merge-patch+json"})
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=5)
        assert exc.value.code == 409


# CI shard (pyproject [tool.pytest.ini_options] markers)
pytestmark = pytest.mark.control_plane
