"""Pallas flash attention vs the XLA reference implementation.

Runs the kernels in interpreter mode (CPU); the driver's TPU bench runs
them compiled. Mirrors the reference's golden-comparison style
(pod_test.go TestClusterSpec analog for numerics).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tf_operator_tpu.ops.flash_attention import (
    flash_attention,
    flash_supported,
)
from tf_operator_tpu.ops.layers import attention


def make_qkv(b=1, s=256, h=2, d=128, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (b, s, h, d)
    return tuple(jax.random.normal(k, shape, dtype) * 0.5 for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_reference(causal):
    q, k, v = make_qkv()
    ref = attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_forward_q_offset():
    # q_offset shifts causal positions (ring/decode blocks).
    q, k, v = make_qkv(s=256)
    q_blk = q[:, :128]
    ref = attention(q_blk, k, v, causal=True, q_offset=128)
    out = flash_attention(q_blk, k, v, causal=True, q_offset=128,
                          interpret=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_gradients_match_reference():
    q, k, v = make_qkv(s=256)

    def loss_ref(q, k, v):
        return jnp.sum(attention(q, k, v, causal=True) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=True, interpret=True) ** 2)

    ref_grads = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    fl_grads = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for g_ref, g_fl, name in zip(ref_grads, fl_grads, "qkv"):
        np.testing.assert_allclose(
            g_fl, g_ref, atol=5e-4, rtol=5e-4,
            err_msg=f"grad mismatch for {name}")


def test_non_causal_gradients():
    q, k, v = make_qkv(s=128)
    f = lambda *a: jnp.sum(
        flash_attention(*a, causal=False, interpret=True) * 0.1)
    r = lambda *a: jnp.sum(attention(*a, causal=False) * 0.1)
    for g_fl, g_ref in zip(jax.grad(f, (0, 1, 2))(q, k, v),
                           jax.grad(r, (0, 1, 2))(q, k, v)):
        np.testing.assert_allclose(g_fl, g_ref, atol=5e-4, rtol=5e-4)


def test_bf16_forward_close():
    q, k, v = make_qkv(dtype=jnp.bfloat16)
    ref = attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, interpret=True)
    np.testing.assert_allclose(out.astype(np.float32),
                               ref.astype(np.float32), atol=2e-2, rtol=2e-2)


def test_sharded_flash_matches_reference():
    # GSPMD path: shard_map over (dp, fsdp, tp) on the 8-device CPU mesh.
    from tf_operator_tpu.ops.flash_attention import flash_attention_sharded
    from tf_operator_tpu.parallel.mesh import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
    q, k, v = make_qkv(b=4, s=128, h=4, d=128)
    ref = attention(q, k, v, causal=True)
    out = flash_attention_sharded(q, k, v, mesh, causal=True,
                                  interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_supported_gate():
    assert flash_supported(256, 256, 128)
    assert not flash_supported(100, 256, 128)   # seq not tileable
    assert not flash_supported(256, 256, 64)    # head_dim < lane width
    with pytest.raises(ValueError):
        bad = jnp.zeros((1, 100, 2, 128))
        flash_attention(bad, bad, bad, interpret=True)


def test_gqa_matches_repeated_kv_reference():
    """GQA: k/v with fewer heads, kernel indexes the shared head — must
    equal the repeated-KV reference for values AND all three grads."""
    from tf_operator_tpu.ops.layers import repeat_kv

    rngs = jax.random.split(jax.random.PRNGKey(3), 3)
    b, s, h, h_kv, d = 2, 128, 4, 2, 128
    q = jax.random.normal(rngs[0], (b, s, h, d), jnp.float32) * 0.1
    k = jax.random.normal(rngs[1], (b, s, h_kv, d), jnp.float32) * 0.1
    v = jax.random.normal(rngs[2], (b, s, h_kv, d), jnp.float32) * 0.1

    def loss_gqa(q, k, v):
        return flash_attention(q, k, v, causal=True,
                               interpret=True).sum()

    def loss_ref(q, k, v):
        return attention(q, repeat_kv(k, h // h_kv),
                         repeat_kv(v, h // h_kv), causal=True).sum()

    out = flash_attention(q, k, v, causal=True, interpret=True)
    ref = attention(q, repeat_kv(k, h // h_kv), repeat_kv(v, h // h_kv),
                    causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

    g_gqa = jax.grad(loss_gqa, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_gqa, g_ref):
        assert a.shape == b_.shape
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=2e-4, rtol=2e-4)


def test_sharded_gqa_matches_reference():
    """GQA KV through shard_map with the head axis sharded over tp."""
    from tf_operator_tpu.ops.flash_attention import flash_attention_sharded
    from tf_operator_tpu.ops.layers import repeat_kv
    from tf_operator_tpu.parallel.mesh import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
    rngs = jax.random.split(jax.random.PRNGKey(5), 3)
    b, s, h, h_kv, d = 4, 128, 4, 2, 128
    q = jax.random.normal(rngs[0], (b, s, h, d), jnp.float32) * 0.1
    k = jax.random.normal(rngs[1], (b, s, h_kv, d), jnp.float32) * 0.1
    v = jax.random.normal(rngs[2], (b, s, h_kv, d), jnp.float32) * 0.1
    ref = attention(q, repeat_kv(k, h // h_kv), repeat_kv(v, h // h_kv),
                    causal=True)
    out = flash_attention_sharded(q, k, v, mesh, causal=True,
                                  interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_best_attention_gqa_tp_indivisible_falls_back():
    """kv heads not divisible by tp: the auto path must fall back to the
    XLA reference instead of crashing in shard_map."""
    from tf_operator_tpu.ops.flash_attention import best_attention
    from tf_operator_tpu.parallel.mesh import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(dp=2, tp=4))
    rngs = jax.random.split(jax.random.PRNGKey(6), 3)
    b, s, h, h_kv, d = 2, 128, 4, 2, 128  # kv=2 not divisible by tp=4
    q = jax.random.normal(rngs[0], (b, s, h, d), jnp.float32) * 0.1
    k = jax.random.normal(rngs[1], (b, s, h_kv, d), jnp.float32) * 0.1
    v = jax.random.normal(rngs[2], (b, s, h_kv, d), jnp.float32) * 0.1
    from tf_operator_tpu.ops.layers import repeat_kv
    ref = attention(q, repeat_kv(k, 2), repeat_kv(v, 2), causal=True)
    out = best_attention(q, k, v, causal=True, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_supported_degenerate_short_seq():
    """seq < 8 cannot form a sublane block: must report unsupported, not
    raise ZeroDivisionError (advisor round-1 medium finding)."""
    assert not flash_supported(4, 2048, 128)
    assert not flash_supported(1, 128, 128)
    assert not flash_supported(128, 4, 128)


def test_best_attention_short_seq_falls_back():
    """Single-token-style decode shapes must dispatch to the XLA
    reference, not crash in flash_supported."""
    from tf_operator_tpu.ops.flash_attention import best_attention

    rngs = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(rngs[0], (1, 4, 2, 128), jnp.float32) * 0.1
    k = jax.random.normal(rngs[1], (1, 4, 2, 128), jnp.float32) * 0.1
    v = jax.random.normal(rngs[2], (1, 4, 2, 128), jnp.float32) * 0.1
    ref = attention(q, k, v, causal=True)
    out = best_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_best_attention_rejects_indivisible_gqa_heads():
    """q heads % kv heads != 0 must raise the descriptive GQA error on
    the fallback path too, not an opaque einsum shape error."""
    from tf_operator_tpu.ops.flash_attention import best_attention

    q = jnp.zeros((1, 128, 4, 128))
    kv = jnp.zeros((1, 128, 3, 128))
    with pytest.raises(ValueError, match="GQA head counts"):
        best_attention(q, kv, kv, causal=True)

# CI shard (pyproject [tool.pytest.ini_options] markers)
pytestmark = pytest.mark.compute


class TestLseContract:
    """lse is a non-differentiable auxiliary output (contract at
    _flash): _flash_bwd discards its cotangent, and anything exposing
    lse must gate it through _guard_lse_nondiff so a differentiating
    caller fails loudly instead of training with silent zero grads
    (round-5 advisory)."""

    def _flash_outputs(self, q, k, v):
        from tf_operator_tpu.ops.flash_attention import _flash

        qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
        return _flash(qt, kt, vt, True, 0, 256, 256, True)

    def test_guard_raises_on_lse_differentiation(self):
        from tf_operator_tpu.ops.flash_attention import _guard_lse_nondiff

        q, k, v = make_qkv()

        def loss(q):
            _, lse = self._flash_outputs(q, k, v)
            return jnp.sum(_guard_lse_nondiff(lse))

        with pytest.raises(NotImplementedError, match="lse"):
            jax.grad(loss)(q)

    def test_guard_is_identity_forward(self):
        from tf_operator_tpu.ops.flash_attention import _guard_lse_nondiff

        q, k, v = make_qkv()
        _, lse = self._flash_outputs(q, k, v)
        np.testing.assert_array_equal(_guard_lse_nondiff(lse), lse)

    def test_bwd_discards_lse_cotangent(self):
        """Pins the documented _flash_bwd contract: an UNGATED lse
        consumer gets exactly-zero grads (why the guard exists). If
        this ever starts returning nonzero, the lse cotangent was
        implemented — delete the guard and this pin together."""
        q, k, v = make_qkv()

        def loss(q):
            _, lse = self._flash_outputs(q, k, v)
            return jnp.sum(lse)

        grads = jax.grad(loss)(q)
        np.testing.assert_array_equal(np.asarray(grads), 0.0)

    def test_out_gradients_unaffected_by_guard_presence(self):
        q, k, v = make_qkv()

        def loss_flash(q):
            out, _ = self._flash_outputs(q, k, v)
            return jnp.sum(out.astype(jnp.float32) ** 2)

        def loss_ref(q):
            out = attention(q, k, v, causal=True)
            return jnp.sum(out.astype(jnp.float32) ** 2)

        g1 = jax.grad(loss_flash)(q)
        g2 = jax.grad(loss_ref)(q)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=2e-2, atol=2e-2)
