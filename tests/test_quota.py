"""Tenant-queue quota admission (controller/quota.py): nominal quota,
cohort borrowing, reclaim preemption, and the job-facing arc (Queued
condition, terminal QuotaExceeded, QueueDeleted re-queueing).

Unit level drives SliceGangScheduler + TenantQueueManager directly on a
Store (the test_gang_admission idiom); e2e level runs the full local
Operator with --enable-tenant-queues semantics and real subprocess pods
— including the acceptance arc: two queues over one cohort, the
quota-exceeding tenant waits with QueuedWaitingForQuota while the other
admits, idle capacity is borrowable, and a reclaim preemption restores
nominal quota.
"""

import datetime as dt
import os
import sys
import time

import pytest

from tf_operator_tpu import testutil
from tf_operator_tpu.api import constants
from tf_operator_tpu.api.types import (
    ClusterQueue,
    ClusterQueueSpec,
    Container,
    JobConditionType,
    ObjectMeta,
    PodSpec,
    PodTemplateSpec,
    ReclaimPolicy,
    ReplicaSpec,
    SliceGroup,
    SliceGroupSpec,
    SliceGroupStatus,
    TenantQueue,
    TenantQueueSpec,
    TPUJob,
    TPUJobSpec,
    TPUSliceSpec,
)
from tf_operator_tpu.api.validation import ValidationError
from tf_operator_tpu.controller.gang import (
    PHASE_INQUEUE,
    PHASE_PENDING,
    PHASE_RUNNING,
    SliceGangScheduler,
)
from tf_operator_tpu.controller.quota import (
    TenantQueueManager,
    load_queue_config,
    seed_queues,
)
from tf_operator_tpu.operator import Operator
from tf_operator_tpu.runtime import metrics, store as store_mod
from tf_operator_tpu.runtime.events import Recorder
from tf_operator_tpu.runtime.store import Store
from tf_operator_tpu.sdk import TPUJobClient

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _now():
    return dt.datetime.now(dt.timezone.utc)


def add_cluster_queue(store, name, nominal, borrowing_limit=None,
                      cohort="", reclaim_policy=""):
    cq = ClusterQueue(spec=ClusterQueueSpec(
        nominal_chips=nominal, borrowing_limit=borrowing_limit,
        cohort=cohort, reclaim_policy=reclaim_policy))
    cq.metadata.name = name
    cq.metadata.namespace = ""
    store.create(store_mod.CLUSTERQUEUES, cq)
    return cq


def add_tenant_queue(store, name, cluster_queue, namespace="default"):
    tq = TenantQueue(spec=TenantQueueSpec(cluster_queue=cluster_queue))
    tq.metadata.name = name
    tq.metadata.namespace = namespace
    store.create(store_mod.TENANTQUEUES, tq)
    return tq


def add_group(store, name, chips=8, queue="", priority="",
              phase=PHASE_PENDING, age_seconds=0.0):
    group = SliceGroup(
        spec=SliceGroupSpec(min_member=1, queue=queue,
                            priority_class=priority,
                            slice=TPUSliceSpec(accelerator=f"v5e-{chips}")),
        status=SliceGroupStatus(
            phase=phase,
            pending_since=_now() - dt.timedelta(seconds=age_seconds)))
    group.metadata.name = name
    group.metadata.namespace = "default"
    group.metadata.creation_timestamp = \
        _now() - dt.timedelta(seconds=age_seconds)
    store.create(store_mod.SLICEGROUPS, group)
    return group


def phase_of(store, name):
    return store.get(store_mod.SLICEGROUPS, "default", name).status.phase


def quota_sched(store, total_chips=None, recorder=None, **gang_kwargs):
    mgr = TenantQueueManager(store, recorder=recorder)
    sched = SliceGangScheduler(store, total_chips=total_chips, quota=mgr,
                               **gang_kwargs)
    return sched, mgr


def wait_of(mgr, name, namespace="default"):
    return mgr.status_for(TPUJob(metadata=ObjectMeta(
        name=name, namespace=namespace)))


# --- nominal quota / borrowing (unit) --------------------------------------

def test_default_queue_is_quota_exempt():
    """Groups without a queueName keep pre-quota behavior even with the
    manager wired: the default queue is not metered."""
    store = Store()
    sched, mgr = quota_sched(store, total_chips=8)
    add_cluster_queue(store, "cq-a", nominal=0)
    add_tenant_queue(store, "team-a", "cq-a")
    add_group(store, "legacy", chips=8, queue="")
    sched._admit()
    assert phase_of(store, "legacy") == PHASE_INQUEUE
    assert wait_of(mgr, "legacy") is None


def test_nominal_quota_blocks_one_tenant_while_other_admits():
    """The acceptance core at unit level: two queues over one cohort,
    the quota-exceeding tenant waits (with a recorded wait state) while
    the other tenant admits — physical capacity alone would have fit
    both."""
    store = Store()
    rec = Recorder()
    sched, mgr = quota_sched(store, total_chips=32, recorder=rec)
    add_cluster_queue(store, "cq-a", nominal=8, borrowing_limit=0,
                      cohort="pool")
    add_cluster_queue(store, "cq-b", nominal=8, borrowing_limit=0,
                      cohort="pool")
    add_tenant_queue(store, "team-a", "cq-a")
    add_tenant_queue(store, "team-b", "cq-b")
    add_group(store, "a1", chips=8, queue="team-a", age_seconds=30)
    add_group(store, "a2", chips=8, queue="team-a", age_seconds=20)
    add_group(store, "b1", chips=8, queue="team-b", age_seconds=10)
    sched._admit()
    assert phase_of(store, "a1") == PHASE_INQUEUE
    assert phase_of(store, "a2") == PHASE_PENDING  # over nominal, no borrow
    assert phase_of(store, "b1") == PHASE_INQUEUE  # own lane unaffected
    wait = wait_of(mgr, "a2")
    assert wait is not None and not wait.terminal
    assert "borrowingLimit" in wait.message
    assert rec.events_for("a2", reason="QueuedWaitingForQuota")


def test_idle_cohort_capacity_is_borrowable():
    store = Store()
    rec = Recorder()
    sched, mgr = quota_sched(store, total_chips=32, recorder=rec)
    add_cluster_queue(store, "cq-a", nominal=8, cohort="pool")
    add_cluster_queue(store, "cq-b", nominal=8, cohort="pool")
    add_tenant_queue(store, "team-a", "cq-a")
    add_tenant_queue(store, "team-b", "cq-b")
    add_group(store, "a1", chips=8, queue="team-a", age_seconds=20)
    add_group(store, "a2", chips=8, queue="team-a", age_seconds=10)
    sched._admit()
    # a2 runs on cq-b's idle nominal share.
    assert phase_of(store, "a1") == PHASE_INQUEUE
    assert phase_of(store, "a2") == PHASE_INQUEUE
    assert rec.events_for("a2", reason="BorrowedCapacity")
    cq = store.get(store_mod.CLUSTERQUEUES, "", "cq-a")
    assert cq.status.admitted_chips == 16
    assert cq.status.borrowed_chips == 8
    assert metrics.queue_borrowed_chips.value(queue="cq-a") == 8


def test_borrowing_never_exceeds_cohort_capacity():
    """The subsystem's first invariant: even with unlimited
    borrowingLimit, admissions stop at the cohort's aggregate nominal."""
    store = Store()
    sched, mgr = quota_sched(store, total_chips=1024)
    add_cluster_queue(store, "cq-a", nominal=8, cohort="pool")
    add_cluster_queue(store, "cq-b", nominal=8, cohort="pool")
    add_tenant_queue(store, "team-a", "cq-a")
    add_tenant_queue(store, "team-b", "cq-b")
    for i in range(4):  # 32 chips requested over a 16-chip cohort
        add_group(store, f"a{i}", chips=8, queue="team-a",
                  age_seconds=40 - i)
    sched._admit()
    admitted = [f"a{i}" for i in range(4)
                if phase_of(store, f"a{i}") == PHASE_INQUEUE]
    assert admitted == ["a0", "a1"]  # FIFO, 16/16 cohort chips
    wait = wait_of(mgr, "a2")
    assert wait is not None and "no idle capacity" in wait.message


def test_borrowing_limit_caps_borrow_below_cohort_idle():
    store = Store()
    sched, mgr = quota_sched(store, total_chips=64)
    add_cluster_queue(store, "cq-a", nominal=8, borrowing_limit=4,
                      cohort="pool")
    add_cluster_queue(store, "cq-b", nominal=16, cohort="pool")
    add_tenant_queue(store, "team-a", "cq-a")
    add_tenant_queue(store, "team-b", "cq-b")
    add_group(store, "a1", chips=8, queue="team-a", age_seconds=20)
    add_group(store, "a2", chips=8, queue="team-a", age_seconds=10)
    sched._admit()
    assert phase_of(store, "a1") == PHASE_INQUEUE
    # 8 over nominal > borrowingLimit 4, despite 16 idle cohort chips.
    assert phase_of(store, "a2") == PHASE_PENDING
    assert "borrowingLimit" in wait_of(mgr, "a2").message


def test_fifo_within_priority_preserved_inside_queue():
    """Starvation-freedom invariant: a quota-blocked group holds its
    FIFO slot — a younger same-queue group must not leapfrog it when
    quota frees (lane blocking applies to quota blocks too)."""
    store = Store()
    sched, mgr = quota_sched(store, total_chips=64, fairness="strict")
    add_cluster_queue(store, "cq-a", nominal=8, borrowing_limit=0)
    add_tenant_queue(store, "team-a", "cq-a")
    add_group(store, "hog", chips=8, queue="team-a", phase=PHASE_INQUEUE)
    add_group(store, "older", chips=8, queue="team-a", age_seconds=20)
    add_group(store, "younger", chips=4, queue="team-a", age_seconds=10)
    sched._admit()
    # Strict lane: younger (4 chips would fit nominal? no — hog holds
    # 8/8) must not admit past the blocked older group either way.
    assert phase_of(store, "older") == PHASE_PENDING
    assert phase_of(store, "younger") == PHASE_PENDING
    # Quota frees: the OLDER group takes the slot first.
    store.delete(store_mod.SLICEGROUPS, "default", "hog")
    sched._admit()
    assert phase_of(store, "older") == PHASE_INQUEUE
    assert phase_of(store, "younger") == PHASE_PENDING


# --- reclaim (unit) --------------------------------------------------------

def test_borrow_then_reclaim_restores_nominal_within_one_pass():
    """Borrow-then-reclaim convergence: a single admission pass issues
    the reclaim displacement AND (pod-free groups) admits the nominal
    demander — the cohort returns to nominal without waiting for a
    resync."""
    store = Store()
    rec = Recorder()
    sched, mgr = quota_sched(store, total_chips=16, recorder=rec)
    add_cluster_queue(store, "cq-a", nominal=8, cohort="pool")
    add_cluster_queue(store, "cq-b", nominal=8, cohort="pool")
    add_tenant_queue(store, "team-a", "cq-a")
    add_tenant_queue(store, "team-b", "cq-b")
    add_group(store, "a1", chips=8, queue="team-a", age_seconds=30)
    add_group(store, "a2", chips=8, queue="team-a", age_seconds=20)
    sched._admit()
    assert phase_of(store, "a2") == PHASE_INQUEUE  # borrowed
    before = metrics.quota_reclaims.value(queue="team-a")

    add_group(store, "b1", chips=8, queue="team-b", age_seconds=10)
    sched._admit()  # ONE pass: reclaim a2, admit b1
    assert phase_of(store, "a2") == PHASE_PENDING
    assert phase_of(store, "b1") == PHASE_INQUEUE
    assert phase_of(store, "a1") == PHASE_INQUEUE  # never below nominal
    assert metrics.quota_reclaims.value(queue="team-a") == before + 1
    assert rec.events_for("a2", reason="QuotaReclaimed")
    cq_a = store.get(store_mod.CLUSTERQUEUES, "", "cq-a")
    assert cq_a.status.admitted_chips == 8
    assert cq_a.status.borrowed_chips == 0


def test_reclaim_never_takes_a_queue_below_nominal():
    """Only the borrowed portion is reclaimable: with one 8-chip
    borrower, a 16-chip nominal demand reclaims the borrower and then
    stops — the lender's within-nominal gang survives."""
    store = Store()
    sched, mgr = quota_sched(store, total_chips=32)
    add_cluster_queue(store, "cq-a", nominal=16, cohort="pool")
    add_cluster_queue(store, "cq-b", nominal=16, cohort="pool")
    add_tenant_queue(store, "team-a", "cq-a")
    add_tenant_queue(store, "team-b", "cq-b")
    add_group(store, "a1", chips=16, queue="team-a", age_seconds=30)
    add_group(store, "a2", chips=8, queue="team-a", age_seconds=20)
    sched._admit()
    add_group(store, "b1", chips=16, queue="team-b", age_seconds=10)
    sched._admit()
    assert phase_of(store, "a1") == PHASE_INQUEUE  # within nominal: kept
    assert phase_of(store, "a2") == PHASE_PENDING  # the borrower: evicted
    assert phase_of(store, "b1") == PHASE_INQUEUE


def test_reclaim_policy_never_waits_for_voluntary_free():
    store = Store()
    sched, mgr = quota_sched(store, total_chips=16)
    add_cluster_queue(store, "cq-a", nominal=8, cohort="pool")
    add_cluster_queue(store, "cq-b", nominal=8, cohort="pool",
                      reclaim_policy=ReclaimPolicy.NEVER)
    add_tenant_queue(store, "team-a", "cq-a")
    add_tenant_queue(store, "team-b", "cq-b")
    add_group(store, "a1", chips=8, queue="team-a", age_seconds=30)
    add_group(store, "a2", chips=8, queue="team-a", age_seconds=20)
    sched._admit()
    add_group(store, "b1", chips=8, queue="team-b", age_seconds=10)
    sched._admit()
    # b1's queue never reclaims: the borrower keeps running, b1 waits.
    assert phase_of(store, "a2") == PHASE_INQUEUE
    assert phase_of(store, "b1") == PHASE_PENDING
    # But the borrow freeze holds: a new borrow attempt is denied while
    # b1's nominal demand is outstanding.
    add_group(store, "a3", chips=8, queue="team-a", age_seconds=5)
    sched._admit()
    assert phase_of(store, "a3") == PHASE_PENDING


def test_reclaim_policy_lower_priority_spares_equal_priority():
    store = Store()
    sched, mgr = quota_sched(
        store, total_chips=16,
        priority_classes={"prod": 100, "batch": 10})
    add_cluster_queue(store, "cq-a", nominal=8, cohort="pool")
    add_cluster_queue(store, "cq-b", nominal=8, cohort="pool",
                      reclaim_policy=ReclaimPolicy.LOWER_PRIORITY)
    add_tenant_queue(store, "team-a", "cq-a")
    add_tenant_queue(store, "team-b", "cq-b")
    add_group(store, "a1", chips=8, queue="team-a", priority="prod",
              age_seconds=30)
    add_group(store, "a2", chips=8, queue="team-a", priority="prod",
              age_seconds=20)
    sched._admit()
    add_group(store, "b1", chips=8, queue="team-b", priority="prod",
              age_seconds=10)
    sched._admit()
    # The borrower is equal priority: LowerPriority reclaim spares it.
    assert phase_of(store, "a2") == PHASE_INQUEUE
    assert phase_of(store, "b1") == PHASE_PENDING
    # A lower-priority borrower in the same spot IS reclaimed.
    store.delete(store_mod.SLICEGROUPS, "default", "b1")
    store.delete(store_mod.SLICEGROUPS, "default", "a2")
    sched._admit()
    add_group(store, "a3", chips=8, queue="team-a", priority="batch",
              age_seconds=8)
    sched._admit()
    assert phase_of(store, "a3") == PHASE_INQUEUE  # borrows
    add_group(store, "b2", chips=8, queue="team-b", priority="prod",
              age_seconds=5)
    sched._admit()
    assert phase_of(store, "a3") == PHASE_PENDING
    assert phase_of(store, "b2") == PHASE_INQUEUE


# --- terminal / orphan edges (unit) ----------------------------------------

def test_zero_quota_queue_is_terminal():
    """A queue that can never hold the group (nominal 0, borrowing 0)
    reports a TERMINAL wait — the engine turns it into a Failed
    condition with reason QuotaExceeded rather than queueing forever."""
    store = Store()
    sched, mgr = quota_sched(store, total_chips=64)
    add_cluster_queue(store, "cq-zero", nominal=0, borrowing_limit=0)
    add_tenant_queue(store, "team-zero", "cq-zero")
    add_group(store, "z1", chips=8, queue="team-zero")
    sched._admit()
    assert phase_of(store, "z1") == PHASE_PENDING
    wait = wait_of(mgr, "z1")
    assert wait is not None and wait.terminal
    assert "can hold at most 0" in wait.message
    # Terminal groups must not block their lane: a sibling with real
    # quota behind them still admits.
    add_cluster_queue(store, "cq-real", nominal=8)
    add_tenant_queue(store, "team-real", "cq-real")
    add_group(store, "r1", chips=8, queue="team-real")
    sched._admit()
    assert phase_of(store, "r1") == PHASE_INQUEUE


def test_deleted_tenant_queue_requeues_to_default_with_event():
    """TenantQueue deleted with pending groups: the groups fall back to
    the default (quota-exempt) queue and a QueueDeleted event says so —
    once, not per pass."""
    store = Store()
    rec = Recorder()
    sched, mgr = quota_sched(store, total_chips=8, recorder=rec)
    add_cluster_queue(store, "cq-a", nominal=0, borrowing_limit=0)
    tq = add_tenant_queue(store, "team-a", "cq-a")
    add_group(store, "g1", chips=8, queue="team-a")
    sched._admit()
    assert phase_of(store, "g1") == PHASE_PENDING  # zero quota
    store.delete(store_mod.TENANTQUEUES, tq.metadata.namespace,
                 tq.metadata.name)
    sched._admit()
    # Default queue is quota-exempt: the group admits on capacity.
    assert phase_of(store, "g1") == PHASE_INQUEUE
    events = rec.events_for("g1", reason="QueueDeleted")
    assert len(events) == 1
    sched._admit()
    assert len(rec.events_for("g1", reason="QueueDeleted")) == 1


def test_dangling_cluster_queue_waits_non_terminally():
    """A TenantQueue whose ClusterQueue doesn't exist must HOLD its
    groups (not admit them unmetered) but non-terminally — creating
    the ClusterQueue later unblocks them."""
    store = Store()
    sched, mgr = quota_sched(store, total_chips=8)
    add_tenant_queue(store, "team-a", "cq-later")
    add_group(store, "g1", chips=8, queue="team-a")
    sched._admit()
    assert phase_of(store, "g1") == PHASE_PENDING
    wait = wait_of(mgr, "g1")
    assert wait is not None and not wait.terminal
    assert "does not exist" in wait.message
    add_cluster_queue(store, "cq-later", nominal=8)
    sched._admit()
    assert phase_of(store, "g1") == PHASE_INQUEUE
    assert wait_of(mgr, "g1") is None


def test_quota_applies_with_capacity_provider_unlimited_flag():
    """Quota gates even when the physical budget is unlimited (the
    total_chips=None observability mode): eligibility is orthogonal to
    fit."""
    store = Store()
    sched, mgr = quota_sched(store, total_chips=None)
    add_cluster_queue(store, "cq-a", nominal=8, borrowing_limit=0)
    add_tenant_queue(store, "team-a", "cq-a")
    add_group(store, "a1", chips=8, queue="team-a", age_seconds=20)
    add_group(store, "a2", chips=8, queue="team-a", age_seconds=10)
    sched._admit()
    assert phase_of(store, "a1") == PHASE_INQUEUE
    assert phase_of(store, "a2") == PHASE_PENDING


# --- config file / seeding -------------------------------------------------

def test_load_queue_config_roundtrip(tmp_path):
    path = tmp_path / "queues.yaml"
    path.write_text("""
clusterQueues:
  - name: pool-a
    nominalChips: 16
    borrowingLimit: 8
    cohort: research
  - name: pool-b
    nominalChips: 8
tenantQueues:
  - name: team-a
    namespace: ns1
    clusterQueue: pool-a
  - name: team-b
    clusterQueue: pool-b
""")
    cqs, tqs = load_queue_config(str(path))
    assert [c.metadata.name for c in cqs] == ["pool-a", "pool-b"]
    assert cqs[0].spec.borrowing_limit == 8
    assert cqs[0].spec.cohort == "research"
    # Defaults applied: cohort-of-one, reclaim Any.
    assert cqs[1].spec.cohort == "pool-b"
    assert cqs[1].spec.reclaim_policy == ReclaimPolicy.ANY
    assert cqs[1].spec.borrowing_limit is None
    assert [(t.metadata.namespace, t.metadata.name) for t in tqs] == [
        ("ns1", "team-a"), ("default", "team-b")]

    store = Store()
    seed_queues(store, cqs, tqs)
    seed_queues(store, cqs, tqs)  # idempotent
    assert store.count(store_mod.CLUSTERQUEUES) == 2
    assert store.count(store_mod.TENANTQUEUES) == 2


def test_load_queue_config_rejects_unknown_and_invalid(tmp_path):
    bad_key = tmp_path / "bad_key.yaml"
    bad_key.write_text("clusterQueues:\n  - name: a\n    nominalChip: 4\n")
    with pytest.raises(ValueError, match="nominalChip"):
        load_queue_config(str(bad_key))
    bad_ref = tmp_path / "bad_ref.yaml"
    bad_ref.write_text("tenantQueues:\n  - name: team-a\n")
    with pytest.raises(ValidationError, match="clusterQueue"):
        load_queue_config(str(bad_ref))
    bad_policy = tmp_path / "bad_policy.yaml"
    bad_policy.write_text("clusterQueues:\n  - name: a\n"
                          "    reclaimPolicy: Sometimes\n")
    with pytest.raises(ValidationError, match="reclaimPolicy"):
        load_queue_config(str(bad_policy))


def test_operator_requires_gang_scheduling_for_tenant_queues():
    with pytest.raises(ValueError, match="gang"):
        Operator(enable_tenant_queues=True, backend=None)


# --- e2e: full local operator ----------------------------------------------

def stub_command(*args):
    return [sys.executable, "-m", "tf_operator_tpu.runtime.worker_stub",
            *args]


def queue_job(name, stub_dir, chips=8, queue="", args=()):
    spec = ReplicaSpec(
        replicas=1,
        template=PodTemplateSpec(spec=PodSpec(containers=[Container(
            name=constants.DEFAULT_CONTAINER_NAME,
            command=stub_command(*args),
            env={"TPUJOB_STUB_DIR": stub_dir},
        )])))
    job = TPUJob(metadata=ObjectMeta(name=name),
                 spec=TPUJobSpec(replica_specs={"worker": spec}))
    job.spec.slice.accelerator = f"v5e-{chips}"
    job.spec.queue_name = queue
    job.spec.run_policy.clean_pod_policy = "None"
    return job


def tell(stub_dir, pod_name, command):
    os.makedirs(stub_dir, exist_ok=True)
    tmp = os.path.join(stub_dir, f".{pod_name}.cmd.tmp")
    with open(tmp, "w") as f:
        f.write(command)
    os.replace(tmp, os.path.join(stub_dir, f"{pod_name}.cmd"))


def wait_for(predicate, timeout=20.0, interval=0.05, message="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        result = predicate()
        if result:
            return result
        time.sleep(interval)
    pytest.fail(f"timed out waiting for {message}")


def tenant_operator(total_chips, queues):
    """Operator.local with tenant queues on; ``queues`` is
    {tenant: (cluster_queue, nominal, borrowing_limit, cohort)}."""
    op = Operator.local(workdir=REPO_ROOT, enable_gang_scheduling=True,
                        total_chips=total_chips,
                        enable_tenant_queues=True)
    for tenant, (cqn, nominal, bl, cohort) in queues.items():
        if op.store.try_get(store_mod.CLUSTERQUEUES, "", cqn) is None:
            add_cluster_queue(op.store, cqn, nominal=nominal,
                              borrowing_limit=bl, cohort=cohort)
        add_tenant_queue(op.store, tenant, cqn)
    return op


def test_e2e_two_tenants_one_cohort_quota_wait_and_release(tmp_path):
    """The acceptance arc minus reclaim: tenant A exceeds its quota and
    its second job carries QueuedWaitingForQuota while tenant B's job
    admits and runs; when A's first job finishes, the queued job admits
    and the Queued condition resolves to False."""
    op = tenant_operator(16, {
        "team-a": ("cq-a", 8, 0, "pool"),
        "team-b": ("cq-b", 8, 0, "pool"),
    })
    op.start(threadiness=2)
    try:
        client = TPUJobClient(op.store)
        stub_dir = str(tmp_path / "stub")
        client.create(queue_job("a1", stub_dir, chips=8, queue="team-a"))
        client.create(queue_job("a2", stub_dir, chips=8, queue="team-a"))
        client.create(queue_job("b1", stub_dir, chips=8, queue="team-b",
                                args=("--exit-after", "0.3")))

        # b1 admits and completes despite a2 queueing ahead of it.
        job = client.wait_for_job("b1", timeout=30)
        assert testutil.check_condition(job, JobConditionType.SUCCEEDED)

        # a1 runs; a2 is quota-held with a live Queued condition.
        wait_for(lambda: any(p.status.phase == "Running"
                             for p in client.get_pods("a1")),
                 message="a1 running")
        wait_for(lambda: testutil.check_condition(
            client.get("a2"), JobConditionType.QUEUED,
            reason="QueuedWaitingForQuota"), message="a2 Queued condition")
        assert not any(p.status.phase == "Running"
                       for p in client.get_pods("a2"))

        # a1 finishes -> its chips return -> a2 admits, Queued resolves.
        tell(stub_dir, "a1-worker-0", "exit:0")
        client.wait_for_job("a1", timeout=30)
        wait_for(lambda: any(p.status.phase == "Running"
                             for p in client.get_pods("a2")),
                 timeout=30, message="a2 admitted after a1 freed quota")
        wait_for(lambda: testutil.get_condition(
            client.get("a2"), JobConditionType.QUEUED).status == "False",
            timeout=30, message="a2 Queued condition resolved to False")
        tell(stub_dir, "a2-worker-0", "exit:0")
        job = client.wait_for_job("a2", timeout=30)
        assert testutil.check_condition(job, JobConditionType.SUCCEEDED)
    finally:
        op.stop()


def test_e2e_reclaim_preemption_evicts_borrowers_running_pods(tmp_path):
    """Full reclaim arc with real processes: tenant A borrows B's idle
    nominal share and RUNS on it; B's job arrives, the borrowed gang is
    displaced (its pod actually dies), B runs to completion on its
    reclaimed share, and the borrower re-admits afterwards."""
    op = tenant_operator(16, {
        "team-a": ("cq-a", 8, None, "pool"),
        "team-b": ("cq-b", 8, None, "pool"),
    })
    op.start(threadiness=2)
    try:
        client = TPUJobClient(op.store)
        stub_dir = str(tmp_path / "stub")
        client.create(queue_job("a1", stub_dir, chips=8, queue="team-a"))
        client.create(queue_job("a2", stub_dir, chips=8, queue="team-a"))
        # Both run: a2 on borrowed capacity.
        for name in ("a1", "a2"):
            wait_for(lambda n=name: any(
                p.status.phase == "Running"
                for p in client.get_pods(n)), message=f"{name} running")

        client.create(queue_job("b1", stub_dir, chips=8, queue="team-b",
                                args=("--exit-after", "0.5")))
        # The borrower's pod is evicted for the reclaim...
        wait_for(lambda: all(p.status.phase == "Pending"
                             for p in client.get_pods("a2")),
                 timeout=30, message="borrower a2 evicted")
        assert phase_of(op.store, "a1") in (PHASE_INQUEUE, PHASE_RUNNING)
        # ...and the demander completes on its reclaimed nominal share.
        job = client.wait_for_job("b1", timeout=30)
        assert testutil.check_condition(job, JobConditionType.SUCCEEDED)
        assert op.recorder.events_for("a2", reason="QuotaReclaimed")

        # Cohort idle again: the borrower re-admits and converges.
        wait_for(lambda: any(p.status.phase == "Running"
                             for p in client.get_pods("a2")),
                 timeout=30, message="borrower re-admitted")
        for name in ("a1", "a2"):
            tell(stub_dir, f"{name}-worker-0", "exit:0")
            job = client.wait_for_job(name, timeout=30)
            assert testutil.check_condition(job, JobConditionType.SUCCEEDED)
    finally:
        op.stop()


def test_e2e_zero_quota_queue_fails_job_terminally(tmp_path):
    op = tenant_operator(16, {"team-zero": ("cq-zero", 0, 0, "solo")})
    op.start(threadiness=2)
    try:
        client = TPUJobClient(op.store)
        stub_dir = str(tmp_path / "stub")
        client.create(queue_job("doomed", stub_dir, chips=8,
                                queue="team-zero"))
        job = client.wait_for_job("doomed", timeout=30)
        failed = testutil.get_condition(job, JobConditionType.FAILED)
        assert failed is not None and failed.reason == "QuotaExceeded"
        assert client.get_pods("doomed") == []
    finally:
        op.stop()


def test_e2e_queue_name_inert_without_tenant_queues(tmp_path):
    """Flag off = today's behavior: spec.queueName rides along as a
    fairness lane but nothing is metered and no Queued condition ever
    appears."""
    op = Operator.local(workdir=REPO_ROOT, enable_gang_scheduling=True,
                        total_chips=8)
    op.start(threadiness=2)
    try:
        client = TPUJobClient(op.store)
        stub_dir = str(tmp_path / "stub")
        client.create(queue_job("plain", stub_dir, chips=8,
                                queue="team-a",
                                args=("--exit-after", "0.3")))
        job = client.wait_for_job("plain", timeout=30)
        assert testutil.check_condition(job, JobConditionType.SUCCEEDED)
        assert testutil.get_condition(job, JobConditionType.QUEUED) is None
        group_phases = [g.status.phase for g in
                        op.store.list(store_mod.SLICEGROUPS)]
        assert PHASE_PENDING not in group_phases
    finally:
        op.stop()


# CI shard (pyproject [tool.pytest.ini_options] markers)
pytestmark = pytest.mark.control_plane
