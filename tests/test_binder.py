"""Slice-gang binder placement tests (controller/binder.py).

The reference delegated binding to an external Volcano scheduler
(common/job_controller.go:218-245 creates the PodGroup; Volcano gates
and binds), so it has no binder logic to test. Here the operator itself
places admitted gang pods; these tests drive ``bind_pass`` directly
against the Store with a stub bind endpoint, asserting the placement
contract: slice atomicity inside one ICI domain, all-or-nothing per
slice, admission-gated, priority-ordered, restart-pinned, and settled
on bind races.
"""

from typing import Dict, List, Optional

import pytest

from tf_operator_tpu.api import constants
from tf_operator_tpu.api.types import (
    Container,
    Node,
    NodeSpec,
    ObjectMeta,
    Pod,
    PodSpec,
    SliceGroup,
    SliceGroupSpec,
    SliceGroupStatus,
    TPUSliceSpec,
)
from tf_operator_tpu.controller.binder import (
    SliceGangBinder,
    node_ici_domain,
    pod_chip_demand,
)
from tf_operator_tpu.runtime import store as store_mod
from tf_operator_tpu.runtime.store import Store


class StubGang:
    """The binder's two touchpoints on the scheduler, isolated."""

    def __init__(self):
        self.readmits = 0

    def _priority_of(self, sg) -> int:
        try:
            return int(sg.spec.priority_class or 0)
        except ValueError:
            return 0

    def readmit(self) -> None:
        self.readmits += 1


class StubBindClient:
    """pods/binding endpoint semantics against the same Store: first
    bind wins, a second bind 409s (kube_fake.bind_pod mirror)."""

    def __init__(self, store: Store):
        self.store = store
        self.binds: List[tuple] = []
        self.fail_names: set = set()
        self.conflict_names: set = set()

    def bind_pod(self, ns: str, name: str, node: str):
        if name in self.fail_names:
            raise OSError("injected bind transport failure")
        if name in self.conflict_names:
            # Mirror-lag race: another binder placed it but the MODIFIED
            # event hasn't reached this binder's cache yet.
            raise store_mod.ConflictError(
                f"pod {ns}/{name} is already assigned to a node")
        pod = self.store.get(store_mod.PODS, ns, name)
        if pod.spec.node_name:
            raise store_mod.ConflictError(
                f"pod {ns}/{name} is already assigned to node "
                f"{pod.spec.node_name}")
        pod.spec.node_name = node
        self.store.update(store_mod.PODS, pod)
        self.binds.append((ns, name, node))


def add_node(store: Store, name: str, chips: int = 8, domain: str = "",
             unschedulable: bool = False, phase: str = "Ready") -> None:
    labels = {constants.LABEL_ICI_DOMAIN: domain} if domain else {}
    node = Node(
        metadata=ObjectMeta(name=name, namespace="", labels=labels),
        spec=NodeSpec(chips=chips, unschedulable=unschedulable))
    node.status.phase = phase
    store.create(store_mod.NODES, node)


def add_group(store: Store, name: str, accelerator: str = "v5e-16",
              num_slices: int = 1, phase: str = "Inqueue",
              priority: str = "") -> SliceGroup:
    sg = SliceGroup(
        spec=SliceGroupSpec(
            min_member=1, priority_class=priority,
            slice=TPUSliceSpec(accelerator=accelerator,
                               num_slices=num_slices)),
        status=SliceGroupStatus(phase=phase))
    sg.metadata.name = name
    sg.metadata.namespace = "default"
    return store.create(store_mod.SLICEGROUPS, sg)


def add_pod(store: Store, group: str, rtype: str, index: int,
            chips: Optional[int] = 8, node: str = "",
            phase: str = "Pending",
            scheduler: str = constants.DEFAULT_GANG_SCHEDULER,
            gang_annotated: bool = True) -> Pod:
    resources: Dict[str, str] = (
        {constants.RESOURCE_TPU: str(chips)} if chips else {})
    pod = Pod(spec=PodSpec(
        containers=[Container(resources=resources)],
        scheduler_name=scheduler, node_name=node))
    pod.metadata.name = f"{group}-{rtype}-{index}"
    pod.metadata.namespace = "default"
    pod.metadata.labels = {
        constants.LABEL_JOB_NAME: group,
        constants.LABEL_REPLICA_TYPE: rtype,
        constants.LABEL_REPLICA_INDEX: str(index),
    }
    if gang_annotated:
        pod.metadata.annotations = {
            constants.ANNOTATION_GANG_GROUP: group,
            constants.ANNOTATION_GANG_TASK: rtype,
        }
    pod.status.phase = phase
    return store.create(store_mod.PODS, pod)


@pytest.fixture
def store():
    return Store()


@pytest.fixture
def gang():
    return StubGang()


@pytest.fixture
def client(store):
    return StubBindClient(store)


@pytest.fixture
def binder(store, client, gang):
    return SliceGangBinder(store, client, gang)


def bound_nodes(client) -> Dict[str, str]:
    return {name: node for _, name, node in client.binds}


class TestHelpers:
    def test_pod_chip_demand_sums_containers(self):
        pod = Pod(spec=PodSpec(containers=[
            Container(resources={constants.RESOURCE_TPU: "4"}),
            Container(resources={constants.RESOURCE_TPU: "2"}),
            Container(resources={"cpu": "1"})]))
        assert pod_chip_demand(pod) == 6

    def test_pod_chip_demand_tolerates_garbage(self):
        pod = Pod(spec=PodSpec(containers=[
            Container(resources={constants.RESOURCE_TPU: "wat"})]))
        assert pod_chip_demand(pod) == 0

    def test_node_ici_domain_precedence(self):
        n = Node(metadata=ObjectMeta(
            name="n1", labels={constants.LABEL_ICI_DOMAIN: "pool-a",
                               constants.LABEL_GKE_NODEPOOL: "gke-b"}))
        assert node_ici_domain(n) == "pool-a"
        n.metadata.labels.pop(constants.LABEL_ICI_DOMAIN)
        assert node_ici_domain(n) == "gke-b"
        n.metadata.labels.clear()
        assert node_ici_domain(n) == "n1"


class TestSliceAtomicity:
    def test_whole_slice_lands_in_one_domain(self, store, client, gang,
                                             binder):
        # v5e-16: 16 chips, 2 hosts x 8. Two domains, each 2 nodes x 8.
        for i in range(2):
            add_node(store, f"a{i}", 8, "dom-a")
            add_node(store, f"b{i}", 8, "dom-b")
        add_group(store, "j1", "v5e-16")
        add_pod(store, "j1", "worker", 0)
        add_pod(store, "j1", "worker", 1)
        assert binder.bind_pass() == 2
        nodes = bound_nodes(client)
        domains = {n[0] for n in nodes.values()}  # a* or b* prefix
        assert len(nodes) == 2 and len(domains) == 1

    def test_no_partial_bind_when_no_domain_fits(self, store, client,
                                                 binder):
        # Each domain has one 8-chip node; the slice needs 16 in ONE
        # domain. All-or-nothing: zero binds, not one.
        add_node(store, "a0", 8, "dom-a")
        add_node(store, "b0", 8, "dom-b")
        add_group(store, "j1", "v5e-16")
        add_pod(store, "j1", "worker", 0)
        add_pod(store, "j1", "worker", 1)
        assert binder.bind_pass() == 0
        assert client.binds == []

    def test_multislice_slices_may_split_across_domains(self, store,
                                                        client, binder):
        # v5e-8 x2 slices: each slice = 1 host of 8 chips. Two domains
        # with one 8-chip node each: slice 0 and slice 1 land on
        # different domains (DCN between slices is by design).
        add_node(store, "a0", 8, "dom-a")
        add_node(store, "b0", 8, "dom-b")
        add_group(store, "j1", "v5e-8", num_slices=2)
        add_pod(store, "j1", "worker", 0)
        add_pod(store, "j1", "worker", 1)
        assert binder.bind_pass() == 2
        assert set(bound_nodes(client).values()) == {"a0", "b0"}

    def test_partially_bound_slice_pins_domain(self, store, client,
                                               binder):
        # worker-0 already runs in dom-b; the restarted worker-1 must
        # follow it there even though dom-a has more free chips.
        for i in range(2):
            add_node(store, f"a{i}", 8, "dom-a")
            add_node(store, f"b{i}", 8, "dom-b")
        add_group(store, "j1", "v5e-16")
        add_pod(store, "j1", "worker", 0, node="b0", phase="Running")
        add_pod(store, "j1", "worker", 1)
        assert binder.bind_pass() == 1
        assert bound_nodes(client)["j1-worker-1"] == "b1"


class TestAdmissionGate:
    def test_unadmitted_group_stays_unbound(self, store, client, binder):
        add_node(store, "a0", 16, "dom-a")
        add_group(store, "j1", "v5e-16", phase="Pending")
        add_pod(store, "j1", "worker", 0)
        add_pod(store, "j1", "worker", 1)
        assert binder.bind_pass() == 0

    def test_missing_group_stays_unbound(self, store, client, binder):
        add_node(store, "a0", 16, "dom-a")
        add_pod(store, "orphan", "worker", 0)
        assert binder.bind_pass() == 0

    def test_non_gang_pods_ignored(self, store, client, binder):
        add_node(store, "a0", 16, "dom-a")
        add_group(store, "j1", "v5e-16")
        add_pod(store, "j1", "worker", 0, scheduler="")
        assert binder.bind_pass() == 0

    def test_priority_group_binds_first_under_scarcity(self, store,
                                                       client, binder):
        # One 8-chip domain; two single-host groups admitted. The
        # higher-priority one gets the chips regardless of creation
        # order.
        add_node(store, "a0", 8, "dom-a")
        add_group(store, "low", "v5e-8", priority="1")
        add_group(store, "high", "v5e-8", priority="100")
        add_pod(store, "low", "worker", 0)
        add_pod(store, "high", "worker", 0)
        assert binder.bind_pass() == 1
        assert bound_nodes(client) == {"high-worker-0": "a0"}


class TestInventory:
    def test_bound_pods_consume_chips(self, store, client, binder):
        add_node(store, "a0", 8, "dom-a")
        add_group(store, "j1", "v5e-8")
        # A foreign bound pod holds 4 of the 8 chips.
        foreign = Pod(spec=PodSpec(
            containers=[Container(
                resources={constants.RESOURCE_TPU: "4"})],
            node_name="a0"))
        foreign.metadata.name = "foreign"
        foreign.metadata.namespace = "default"
        foreign.status.phase = "Running"
        store.create(store_mod.PODS, foreign)
        add_pod(store, "j1", "worker", 0)  # needs 8
        assert binder.bind_pass() == 0

    def test_terminal_bound_pods_release_chips(self, store, client,
                                               binder):
        add_node(store, "a0", 8, "dom-a")
        add_group(store, "j1", "v5e-8")
        done = add_pod(store, "done", "worker", 0, node="a0",
                       phase="Succeeded")
        assert done.spec.node_name == "a0"
        add_pod(store, "j1", "worker", 0)
        assert binder.bind_pass() == 1

    def test_cordoned_node_skipped_everywhere(self, store, client,
                                              binder):
        add_node(store, "a0", 8, "dom-a", unschedulable=True)
        add_group(store, "j1", "v5e-8")
        add_pod(store, "j1", "worker", 0)
        assert binder.bind_pass() == 0

    def test_notready_node_skipped(self, store, client, binder):
        """A dead kubelet's Node persists with Ready=False; a direct
        pods/binding POST would bypass the not-ready taint filter, so
        the binder must apply it itself."""
        add_node(store, "a0", 8, "dom-a", phase="NotReady")
        add_group(store, "j1", "v5e-8")
        add_pod(store, "j1", "worker", 0)
        assert binder.bind_pass() == 0

    def test_cordoned_peer_still_pins_slice_domain(self, store, client,
                                                   binder):
        """worker-0 runs on a now-cordoned dom-b node; recreated
        worker-1 must still follow the slice into dom-b (placing it in
        dom-a would split the slice across ICI domains)."""
        for i in range(2):
            add_node(store, f"a{i}", 8, "dom-a")
        add_node(store, "b0", 8, "dom-b", unschedulable=True)
        add_node(store, "b1", 8, "dom-b")
        add_group(store, "j1", "v5e-16")
        add_pod(store, "j1", "worker", 0, node="b0", phase="Running")
        add_pod(store, "j1", "worker", 1)
        assert binder.bind_pass() == 1
        assert bound_nodes(client)["j1-worker-1"] == "b1"

    def test_conflict_consumes_chips_in_pass(self, store, client,
                                             binder):
        """A 409 on bind proves the chips are contested: the pass must
        not hand the same node to another group."""
        add_node(store, "a0", 8, "dom-a")
        add_group(store, "j1", "v5e-8", priority="100")
        add_group(store, "j2", "v5e-8", priority="1")
        add_pod(store, "j1", "worker", 0)
        add_pod(store, "j2", "worker", 0)
        client.conflict_names.add("j1-worker-0")
        assert binder.bind_pass() == 0
        assert client.binds == []  # j2 must NOT take the contested node

    def test_node_change_triggers_readmit(self, store, client, gang,
                                          binder):
        binder.bind_pass()
        assert gang.readmits == 1  # first inventory observation
        binder.bind_pass()
        assert gang.readmits == 1  # unchanged: no re-admission churn
        add_node(store, "a0", 8, "dom-a")
        binder.bind_pass()
        assert gang.readmits == 2


class TestFlexiblePods:
    def test_coordinator_pod_binds_anywhere(self, store, client, binder):
        add_node(store, "a0", 8, "dom-a")
        add_group(store, "j1", "v5e-8")
        add_pod(store, "j1", "chief", 0, chips=None)
        add_pod(store, "j1", "worker", 0)
        assert binder.bind_pass() == 2
        assert "j1-chief-0" in bound_nodes(client)

    def test_coordinator_prefers_most_free_node(self, store, client,
                                                binder):
        add_node(store, "small", 2, "dom-a")
        add_node(store, "big", 8, "dom-b")
        add_group(store, "j1", "v5e-8")
        add_pod(store, "j1", "chief", 0, chips=None)
        assert binder.bind_pass() == 1
        assert bound_nodes(client)["j1-chief-0"] == "big"


class TestBindRaces:
    def test_conflict_is_settled_not_error(self, store, client, binder):
        add_node(store, "a0", 8, "dom-a")
        add_group(store, "j1", "v5e-8")
        pod = add_pod(store, "j1", "worker", 0)
        # Another binder wins the race after our cache snapshot: the
        # stub raises Conflict because node_name is already set.
        pod.spec.node_name = "a0"
        store.update(store_mod.PODS, pod)
        # Stale cache view: pass sees it unbound via the fetched list —
        # simulate by operating on a pre-race listing.
        assert binder.bind_pass() == 0  # conflict -> not counted

    def test_transport_failure_retries_next_pass(self, store, client,
                                                 binder):
        add_node(store, "a0", 8, "dom-a")
        add_group(store, "j1", "v5e-8")
        add_pod(store, "j1", "worker", 0)
        client.fail_names.add("j1-worker-0")
        assert binder.bind_pass() == 0
        client.fail_names.clear()
        assert binder.bind_pass() == 1


class TestBestFit:
    def test_smallest_fitting_domain_chosen(self, store, client, binder):
        # dom-big could fit the slice with room to spare; dom-tight fits
        # exactly. Best-fit keeps the big domain whole.
        add_node(store, "big0", 8, "dom-big")
        add_node(store, "big1", 8, "dom-big")
        add_node(store, "tight", 8, "dom-tight")
        add_group(store, "j1", "v5e-8")
        add_pod(store, "j1", "worker", 0)
        assert binder.bind_pass() == 1
        assert bound_nodes(client)["j1-worker-0"] == "tight"

    def test_sub_host_slices_pack_one_node(self, store, client, binder):
        # Two v5e-4 groups (4 chips, single host) share one 8-chip node.
        add_node(store, "a0", 8, "dom-a")
        for name in ("j1", "j2"):
            add_group(store, name, "v5e-4")
            add_pod(store, name, "worker", 0, chips=4)
        assert binder.bind_pass() == 2
        nodes = bound_nodes(client)
        assert nodes["j1-worker-0"] == "a0" and nodes["j2-worker-0"] == "a0"


def add_maintenance_node(store: Store, name: str, chips: int = 8,
                         domain: str = "") -> None:
    """A node that is Ready and schedulable but carries an advance
    maintenance notice (slice-health cordon may not have landed yet)."""
    from tf_operator_tpu.controller.health import COND_MAINTENANCE

    labels = {constants.LABEL_ICI_DOMAIN: domain} if domain else {}
    node = Node(
        metadata=ObjectMeta(name=name, namespace="", labels=labels),
        spec=NodeSpec(chips=chips))
    node.status.conditions = {"Ready": "True", COND_MAINTENANCE: "True"}
    store.create(store_mod.NODES, node)


class TestMaintenancePreference:
    """HealthPolicy.prefer_spare_capacity: placement steers away from
    maintenance-pending nodes while they are still schedulable."""

    def test_slice_prefers_clean_domain_over_best_fit(
            self, store, client, binder):
        # dom-tight best-fits the slice but is maintenance-pending;
        # clean dom-big must win despite worse fit.
        add_node(store, "big0", 8, "dom-big")
        add_node(store, "big1", 8, "dom-big")
        add_maintenance_node(store, "tight", 8, "dom-tight")
        add_group(store, "j1", "v5e-8")
        add_pod(store, "j1", "worker", 0)
        assert binder.bind_pass() == 1
        assert bound_nodes(client)["j1-worker-0"] in ("big0", "big1")

    def test_coordinator_prefers_clean_node(self, store, client, binder):
        # The pending node has MORE free chips — most-free would pick
        # it; the clean-first key must override.
        add_maintenance_node(store, "pending", 8, "dom-a")
        add_node(store, "clean", 4, "dom-a")
        add_group(store, "j1", "v5e-8")
        add_pod(store, "j1", "chief", 0, chips=None)
        assert binder.bind_pass() == 1
        assert bound_nodes(client)["j1-chief-0"] == "clean"

    def test_pending_capacity_still_used_when_nothing_else_fits(
            self, store, client, binder):
        add_maintenance_node(store, "pending", 8, "dom-a")
        add_group(store, "j1", "v5e-8")
        add_pod(store, "j1", "worker", 0)
        assert binder.bind_pass() == 1
        assert bound_nodes(client)["j1-worker-0"] == "pending"

    def test_policy_opt_out_restores_best_fit(self, store, client,
                                              binder):
        # prefer_spare_capacity=False on the job: pure best-fit again.
        from tf_operator_tpu.api.types import (
            HealthPolicy,
            RunPolicy,
            TPUJob,
            TPUJobSpec,
        )

        add_node(store, "big0", 8, "dom-big")
        add_node(store, "big1", 8, "dom-big")
        add_maintenance_node(store, "tight", 8, "dom-tight")
        job = TPUJob(metadata=ObjectMeta(name="j1", namespace="default"))
        job.spec = TPUJobSpec(run_policy=RunPolicy(
            health_policy=HealthPolicy(enabled=True,
                                       prefer_spare_capacity=False)))
        store.create(store_mod.TPUJOBS, job)
        add_group(store, "j1", "v5e-8")
        add_pod(store, "j1", "worker", 0)
        assert binder.bind_pass() == 1
        assert bound_nodes(client)["j1-worker-0"] == "tight"


class TestPartialComplementGate:
    def test_partial_slice_waits_for_full_complement(
            self, store, client, binder):
        """A 2-host slice with only one pod visible (gang recreation in
        flight) must NOT bind — a singleton placed into a domain that
        cannot hold the rest splits the slice (round-6 drain e2e)."""
        add_node(store, "a0", 8, "dom-a")          # can hold ONE host
        add_node(store, "b0", 8, "dom-b")
        add_node(store, "b1", 8, "dom-b")
        add_group(store, "j1", "v5e-16")
        add_pod(store, "j1", "worker", 0)
        assert binder.bind_pass() == 0             # waits for worker-1
        add_pod(store, "j1", "worker", 1)
        assert binder.bind_pass() == 2
        nodes = bound_nodes(client)
        assert {nodes["j1-worker-0"], nodes["j1-worker-1"]} == {"b0", "b1"}

    def test_pinned_straggler_still_binds_alone(self, store, client,
                                                binder):
        # Restart case: a peer is already bound, so the lone recreated
        # pod must bind into the pinned domain without waiting.
        add_node(store, "a0", 8, "dom-a")
        add_node(store, "a1", 8, "dom-a")
        add_group(store, "j1", "v5e-16")
        add_pod(store, "j1", "worker", 0, node="a0")
        add_pod(store, "j1", "worker", 1)
        assert binder.bind_pass() == 1
        assert bound_nodes(client)["j1-worker-1"] == "a1"


# CI shard (pyproject [tool.pytest.ini_options] markers)
pytestmark = pytest.mark.control_plane
