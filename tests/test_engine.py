"""Reconcile engine tests.

Mirrors the reference's controller_test.go TestNormalPath matrix,
pod_test.go (scale up/down, exit codes, expectations) and job_test.go
(clean-pod policies, TTL, backoff, deadline).
"""

import datetime as dt

import pytest

from tf_operator_tpu import testutil
from tf_operator_tpu.api import constants, set_defaults
from tf_operator_tpu.api.types import (
    CleanPodPolicy,
    JobConditionType,
    PodPhase,
    RestartPolicy,
)
from tf_operator_tpu.controller import conditions as cond
from tf_operator_tpu.controller.control import FakeEndpointControl, FakePodControl
from tf_operator_tpu.controller.engine import JobEngine
from tf_operator_tpu.controller.expectations import expectation_key


def make_engine(plugin, **kw):
    return JobEngine(plugin=plugin, pod_control=FakePodControl(),
                     endpoint_control=FakeEndpointControl(), **kw)


def run_sync(job, pods=(), endpoints=(), **kw):
    plugin = testutil.StubPlugin(pods=pods, endpoints=endpoints)
    engine = make_engine(plugin, **kw)
    plugin.workqueue = engine.workqueue
    set_defaults(job)
    engine.reconcile_jobs(job)
    return engine, plugin


# ---------------------------------------------------------------------------
# TestNormalPath analog: table of (topology, pod phases) -> expectations
# ---------------------------------------------------------------------------

NORMAL_PATH_CASES = [
    # name, worker, ps, pod phases {rtype: (pending, active, succeeded, failed)},
    # expected creations, deletions, then expected
    # (active, succeeded, failed) tallies per rtype.
    ("all-new", 4, 2, {}, 6, 0, {"worker": (0, 0, 0), "ps": (0, 0, 0)}),
    ("all-pending", 4, 2, {"worker": (4, 0, 0, 0), "ps": (2, 0, 0, 0)},
     0, 0, {"worker": (0, 0, 0), "ps": (0, 0, 0)}),
    ("all-running", 4, 2, {"worker": (0, 4, 0, 0), "ps": (0, 2, 0, 0)},
     0, 0, {"worker": (4, 0, 0), "ps": (2, 0, 0)}),
    ("partial", 4, 2, {"worker": (2, 0, 0, 0), "ps": (1, 0, 0, 0)},
     3, 0, {"worker": (0, 0, 0), "ps": (0, 0, 0)}),
    ("worker-succeeded", 4, 2, {"worker": (0, 0, 4, 0), "ps": (0, 2, 0, 0)},
     0, 0, {"worker": (0, 4, 0), "ps": (2, 0, 0)}),
    ("one-failed", 4, 2, {"worker": (0, 3, 0, 1), "ps": (0, 2, 0, 0)},
     0, 0, {"worker": (3, 0, 1), "ps": (2, 0, 0)}),
]


@pytest.mark.parametrize(
    "name,worker,ps,phases,want_creates,want_deletes,want_statuses",
    NORMAL_PATH_CASES, ids=[c[0] for c in NORMAL_PATH_CASES])
def test_normal_path(name, worker, ps, phases, want_creates, want_deletes,
                     want_statuses):
    job = testutil.new_tpujob(worker=worker, ps=ps)
    pods = []
    for rtype, (pending, active, succeeded, failed) in phases.items():
        testutil.set_pod_statuses(pods, job, rtype, pending=pending,
                                  active=active, succeeded=succeeded,
                                  failed=failed)
    engine, plugin = run_sync(job, pods=pods)
    assert len(engine.pod_control.templates) == want_creates
    assert len(engine.pod_control.delete_pod_names) == want_deletes
    for rtype, (active, succeeded, failed) in want_statuses.items():
        rs = job.status.replica_statuses[rtype]
        assert (rs.active, rs.succeeded, rs.failed) == (active, succeeded, failed), rtype


def test_created_pods_have_identity_labels_and_env():
    job = testutil.new_tpujob(worker=2, ps=1)
    engine, plugin = run_sync(job)
    created = engine.pod_control.templates
    assert len(created) == 3
    names = sorted(p.metadata.name for p in created)
    assert names == ["test-tpujob-ps-0", "test-tpujob-worker-0",
                     "test-tpujob-worker-1"]
    for p in created:
        assert p.metadata.labels[constants.LABEL_GROUP_NAME] == constants.GROUP
        assert p.metadata.labels[constants.LABEL_JOB_NAME] == job.metadata.name
        assert p.metadata.owner_references[0].uid == job.metadata.uid
    # worker-0 is master-role when no chief exists (controller.go:418-425)
    w0 = next(p for p in created if p.metadata.name.endswith("worker-0"))
    assert w0.metadata.labels[constants.LABEL_JOB_ROLE] == "master"
    w1 = next(p for p in created if p.metadata.name.endswith("worker-1"))
    assert constants.LABEL_JOB_ROLE not in w1.metadata.labels
    # cluster spec env injected
    assert w1.spec.containers[0].env["TPU_WORKER_ID"] == "1"


def test_endpoints_created_per_replica():
    job = testutil.new_tpujob(worker=2)
    engine, plugin = run_sync(job)
    eps = engine.endpoint_control.templates
    assert sorted(e.metadata.name for e in eps) == [
        "test-tpujob-worker-0", "test-tpujob-worker-1"]
    for e in eps:
        assert e.spec.ports[constants.DEFAULT_PORT_NAME] == constants.DEFAULT_PORT
        assert e.spec.selector[constants.LABEL_REPLICA_INDEX] in ("0", "1")


def test_scale_down_deletes_out_of_range():
    # Reference pod_test.go TestScaleDown: pods 0,1,2 with replicas=2 ->
    # exactly worker-2 deleted.
    job = testutil.new_tpujob(worker=2)
    pods = testutil.new_pod_list(job, "worker", 3, phase=PodPhase.RUNNING)
    engine, plugin = run_sync(job, pods=pods)
    assert engine.pod_control.delete_pod_names == ["test-tpujob-worker-2"]
    assert engine.pod_control.templates == []


def test_scale_up_creates_missing_indices():
    job = testutil.new_tpujob(worker=4)
    pods = testutil.new_pod_list(job, "worker", 2, phase=PodPhase.RUNNING)
    engine, plugin = run_sync(job, pods=pods)
    assert sorted(p.metadata.name for p in engine.pod_control.templates) == [
        "test-tpujob-worker-2", "test-tpujob-worker-3"]


def test_gap_in_indices_is_refilled():
    job = testutil.new_tpujob(worker=3)
    pods = [testutil.new_pod(job, "worker", 0, phase=PodPhase.RUNNING),
            testutil.new_pod(job, "worker", 2, phase=PodPhase.RUNNING)]
    engine, plugin = run_sync(job, pods=pods)
    assert [p.metadata.name for p in engine.pod_control.templates] == [
        "test-tpujob-worker-1"]


def test_exit_code_retryable_restarts_pod():
    # Reference pod_test.go TestExitCode: failed worker exit 130 -> deleted
    # for restart + Restarting condition.
    job = testutil.new_tpujob(worker=1)
    job.spec.replica_specs["worker"].restart_policy = RestartPolicy.EXIT_CODE
    pods = [testutil.new_pod(job, "worker", 0, phase=PodPhase.FAILED,
                             exit_code=130)]
    engine, plugin = run_sync(job, pods=pods)
    assert engine.pod_control.delete_pod_names == ["test-tpujob-worker-0"]
    assert testutil.check_condition(job, JobConditionType.RESTARTING)
    # restarting in flight: no Failed condition
    assert not cond.is_failed(job.status)


def test_exit_code_restart_with_running_sibling_does_not_fail_job():
    # Regression: a retryable failure on worker-1 while worker-0 is Running
    # must not mark the job Failed (the Running condition clears Restarting
    # via mutual exclusion; the failed>0 guard must use the pre-roll-up
    # restart state).
    job = testutil.new_tpujob(worker=2)
    job.spec.replica_specs["worker"].restart_policy = RestartPolicy.EXIT_CODE
    pods = [testutil.new_pod(job, "worker", 0, phase=PodPhase.RUNNING),
            testutil.new_pod(job, "worker", 1, phase=PodPhase.FAILED,
                             exit_code=137)]
    engine, plugin = run_sync(job, pods=pods)
    assert engine.pod_control.delete_pod_names == ["test-tpujob-worker-1"]
    assert not cond.is_failed(job.status)
    assert cond.is_running(job.status)


def test_exit_code_permanent_fails_job():
    job = testutil.new_tpujob(worker=1)
    job.spec.replica_specs["worker"].restart_policy = RestartPolicy.EXIT_CODE
    pods = [testutil.new_pod(job, "worker", 0, phase=PodPhase.FAILED,
                             exit_code=1)]
    engine, plugin = run_sync(job, pods=pods)
    assert engine.pod_control.delete_pod_names == []
    assert cond.is_failed(job.status)


def test_exit_code_restart_policy_maps_to_never_on_pod():
    # Reference setRestartPolicy (pod.go:319-326).
    job = testutil.new_tpujob(worker=1)
    job.spec.replica_specs["worker"].restart_policy = RestartPolicy.EXIT_CODE
    engine, plugin = run_sync(job)
    assert engine.pod_control.templates[0].spec.restart_policy == RestartPolicy.NEVER


def test_expectations_block_second_create(  ):
    job = testutil.new_tpujob(worker=1)
    engine, plugin = run_sync(job)
    key = expectation_key(job.key(), "pods", "worker")
    assert not engine.expectations.satisfied_expectations(key)
    engine.expectations.creation_observed(key)
    assert engine.expectations.satisfied_expectations(key)


def test_create_error_rolls_back_expectation():
    # Reference pod_test.go TestExpectationWithError.
    job = testutil.new_tpujob(worker=1)
    set_defaults(job)
    plugin = testutil.StubPlugin()
    engine = make_engine(plugin)
    engine.pod_control.create_error = RuntimeError("boom")
    with pytest.raises(RuntimeError):
        engine.reconcile_jobs(job)
    key = expectation_key(job.key(), "pods", "worker")
    assert engine.expectations.satisfied_expectations(key)


# ---------------------------------------------------------------------------
# Success/failure semantics (status_test.go TestStatus analog)
# ---------------------------------------------------------------------------

def run_status(job, pods):
    return run_sync(job, pods=pods)


def test_chief_running_sets_running():
    job = testutil.new_tpujob(worker=2, chief=1)
    pods = testutil.new_pod_list(job, "worker", 2, phase=PodPhase.RUNNING)
    pods += testutil.new_pod_list(job, "chief", 1, phase=PodPhase.RUNNING)
    engine, plugin = run_status(job, pods)
    assert cond.is_running(job.status)
    assert not cond.is_finished(job.status)


def test_chief_succeeded_sets_succeeded():
    job = testutil.new_tpujob(worker=2, chief=1)
    pods = testutil.new_pod_list(job, "worker", 2, phase=PodPhase.RUNNING)
    pods += testutil.new_pod_list(job, "chief", 1, phase=PodPhase.SUCCEEDED)
    engine, plugin = run_status(job, pods)
    assert cond.is_succeeded(job.status)
    assert job.status.completion_time is not None


def test_chief_failed_sets_failed():
    job = testutil.new_tpujob(worker=2, chief=1)
    pods = testutil.new_pod_list(job, "worker", 2, phase=PodPhase.RUNNING)
    pods += testutil.new_pod_list(job, "chief", 1, phase=PodPhase.FAILED)
    engine, plugin = run_status(job, pods)
    assert cond.is_failed(job.status)


def test_worker0_completion_decides_when_chiefless():
    # Reference "(No chief worker) Worker 0 completed" scenario.
    job = testutil.new_tpujob(worker=2)
    pods = [testutil.new_pod(job, "worker", 0, phase=PodPhase.SUCCEEDED),
            testutil.new_pod(job, "worker", 1, phase=PodPhase.RUNNING)]
    engine, plugin = run_status(job, pods)
    assert cond.is_succeeded(job.status)


def test_all_workers_policy_waits_for_all():
    job = testutil.new_tpujob(worker=2)
    job.spec.success_policy = "AllWorkers"
    pods = [testutil.new_pod(job, "worker", 0, phase=PodPhase.SUCCEEDED),
            testutil.new_pod(job, "worker", 1, phase=PodPhase.RUNNING)]
    engine, plugin = run_status(job, pods)
    assert not cond.is_succeeded(job.status)
    assert cond.is_running(job.status)

    pods[1] = testutil.new_pod(job, "worker", 1, phase=PodPhase.SUCCEEDED)
    engine, plugin = run_status(job, pods)
    assert cond.is_succeeded(job.status)


def test_all_replicas_ready_latch():
    # The ready-latency latch fires only when EVERY desired replica is
    # Running/Succeeded, not on the first active pod, and is set once.
    job = testutil.new_tpujob(worker=2, chief=1)
    pods = testutil.new_pod_list(job, "worker", 1, phase=PodPhase.RUNNING)
    pods += testutil.new_pod_list(job, "chief", 1, phase=PodPhase.RUNNING)
    run_status(job, pods)
    assert job.status.all_replicas_ready_time is None  # worker-1 missing

    pods = testutil.new_pod_list(job, "worker", 2, phase=PodPhase.RUNNING)
    pods += testutil.new_pod_list(job, "chief", 1, phase=PodPhase.RUNNING)
    run_status(job, pods)
    first = job.status.all_replicas_ready_time
    assert first is not None

    run_status(job, pods)
    assert job.status.all_replicas_ready_time == first  # latched


def test_worker_failed_chiefless_sets_failed():
    job = testutil.new_tpujob(worker=2)
    pods = [testutil.new_pod(job, "worker", 0, phase=PodPhase.RUNNING),
            testutil.new_pod(job, "worker", 1, phase=PodPhase.FAILED)]
    engine, plugin = run_status(job, pods)
    assert cond.is_failed(job.status)


def test_start_time_set_once():
    job = testutil.new_tpujob(worker=1)
    engine, plugin = run_sync(job)
    t0 = job.status.start_time
    assert t0 is not None
    engine.reconcile_jobs(job)
    assert job.status.start_time == t0


# ---------------------------------------------------------------------------
# RunPolicy: cleanup, TTL, backoff, deadline (job_test.go analog)
# ---------------------------------------------------------------------------

def finished_job(worker=2, policy=CleanPodPolicy.RUNNING):
    job = testutil.new_tpujob(worker=worker)
    set_defaults(job)
    job.spec.run_policy.clean_pod_policy = policy
    cond.update_job_conditions(job.status, JobConditionType.SUCCEEDED,
                               cond.JOB_SUCCEEDED_REASON, "done")
    job.status.completion_time = testutil.now()
    return job


def test_clean_pod_policy_running_keeps_finished_pods():
    job = finished_job(policy=CleanPodPolicy.RUNNING)
    pods = [testutil.new_pod(job, "worker", 0, phase=PodPhase.RUNNING),
            testutil.new_pod(job, "worker", 1, phase=PodPhase.SUCCEEDED)]
    engine, plugin = run_sync(job, pods=pods)
    assert engine.pod_control.delete_pod_names == ["test-tpujob-worker-0"]


def test_clean_pod_policy_all_deletes_everything():
    job = finished_job(policy=CleanPodPolicy.ALL)
    pods = [testutil.new_pod(job, "worker", 0, phase=PodPhase.RUNNING),
            testutil.new_pod(job, "worker", 1, phase=PodPhase.SUCCEEDED)]
    engine, plugin = run_sync(job, pods=pods)
    assert sorted(engine.pod_control.delete_pod_names) == [
        "test-tpujob-worker-0", "test-tpujob-worker-1"]


def test_clean_pod_policy_none_deletes_nothing():
    job = finished_job(policy=CleanPodPolicy.NONE)
    pods = [testutil.new_pod(job, "worker", 0, phase=PodPhase.RUNNING)]
    engine, plugin = run_sync(job, pods=pods)
    assert engine.pod_control.delete_pod_names == []


def test_finished_job_rolls_active_into_succeeded():
    job = finished_job()
    from tf_operator_tpu.api.types import ReplicaStatus

    job.status.replica_statuses["worker"] = ReplicaStatus(active=2, succeeded=0)
    engine, plugin = run_sync(job, pods=[])
    rs = job.status.replica_statuses["worker"]
    assert (rs.active, rs.succeeded) == (0, 2)


def test_ttl_zero_deletes_job_immediately():
    # Reference job_test.go TestCleanupTFJob.
    job = finished_job()
    job.spec.run_policy.ttl_seconds_after_finished = 0
    engine, plugin = run_sync(job, pods=[])
    assert plugin.deleted_jobs == [job.metadata.name]


def test_ttl_future_requeues_instead_of_deleting():
    job = finished_job()
    job.spec.run_policy.ttl_seconds_after_finished = 3600
    engine, plugin = run_sync(job, pods=[])
    assert plugin.deleted_jobs == []
    # Requeued via add_after with the exact remaining TTL (reference
    # job.go:345-357) — NOT add_rate_limited, whose exponential backoff
    # fires early-and-often and pollutes the BackoffLimit counter.
    assert engine.workqueue.num_requeues(job.key()) == 0
    delayed = [(when, item) for when, _, item
               in engine.workqueue._delayed if item == job.key()]
    assert len(delayed) == 1
    import time as _time

    remaining = delayed[0][0] - _time.monotonic()
    # completion_time is ~now, so the delay is ~the full TTL.
    assert 3500 < remaining <= 3600


def test_active_deadline_exceeded_fails_job():
    # Reference job_test.go TestActiveDeadlineSeconds.
    job = testutil.new_tpujob(worker=2)
    job.spec.run_policy.active_deadline_seconds = 1
    job.status.start_time = testutil.now() - dt.timedelta(seconds=5)
    pods = testutil.new_pod_list(job, "worker", 2, phase=PodPhase.RUNNING)
    engine, plugin = run_sync(job, pods=pods)
    assert cond.is_failed(job.status)
    assert sorted(engine.pod_control.delete_pod_names) == [
        "test-tpujob-worker-0", "test-tpujob-worker-1"]


def test_backoff_limit_restart_counts():
    # Reference TestBackoffForOnFailure: running pods whose container
    # restart counts sum >= backoffLimit -> job fails.
    job = testutil.new_tpujob(worker=2)
    job.spec.replica_specs["worker"].restart_policy = RestartPolicy.ON_FAILURE
    job.spec.run_policy.backoff_limit = 3
    pods = testutil.new_pod_list(job, "worker", 2, phase=PodPhase.RUNNING)
    for p in pods:
        from tf_operator_tpu.api.types import ContainerStatus

        p.status.container_statuses = [ContainerStatus(
            name=constants.DEFAULT_CONTAINER_NAME, state="Running",
            restart_count=2)]
    engine, plugin = run_sync(job, pods=pods)
    assert cond.is_failed(job.status)
    failed = testutil.get_condition(job, JobConditionType.FAILED)
    assert "backoff limit" in failed.message


def test_status_written_only_on_change():
    job = testutil.new_tpujob(worker=1)
    pods = testutil.new_pod_list(job, "worker", 1, phase=PodPhase.RUNNING)
    engine, plugin = run_sync(job, pods=pods)
    assert len(plugin.status_writes) == 1
    engine.reconcile_jobs(job)  # no change
    assert len(plugin.status_writes) == 1


def test_evaluator_does_not_decide_success():
    """Reference semantics: the evaluator role never gates job success —
    worker-0 completion succeeds the job while the evaluator still runs,
    and a completed evaluator alone does not succeed it."""
    job = testutil.new_tpujob(worker=2, evaluator=1)
    pods = [testutil.new_pod(job, "worker", 0, phase=PodPhase.SUCCEEDED),
            testutil.new_pod(job, "worker", 1, phase=PodPhase.RUNNING),
            testutil.new_pod(job, "evaluator", 0, phase=PodPhase.RUNNING)]
    engine, plugin = run_status(job, pods)
    assert cond.is_succeeded(job.status)

    job2 = testutil.new_tpujob(worker=2, evaluator=1)
    pods2 = [testutil.new_pod(job2, "worker", 0, phase=PodPhase.RUNNING),
             testutil.new_pod(job2, "worker", 1, phase=PodPhase.RUNNING),
             testutil.new_pod(job2, "evaluator", 0,
                              phase=PodPhase.SUCCEEDED)]
    engine, plugin = run_status(job2, pods2)
    assert not cond.is_succeeded(job2.status)
    assert cond.is_running(job2.status)


def test_evaluator_failure_fails_job():
    """Any replica failure (incl. evaluator) fails the job when not
    restarting (reference status.go failed>0 branch)."""
    job = testutil.new_tpujob(worker=2, evaluator=1)
    pods = [testutil.new_pod(job, "worker", 0, phase=PodPhase.RUNNING),
            testutil.new_pod(job, "worker", 1, phase=PodPhase.RUNNING),
            testutil.new_pod(job, "evaluator", 0, phase=PodPhase.FAILED)]
    engine, plugin = run_status(job, pods)
    assert cond.is_failed(job.status)

# CI shard (pyproject [tool.pytest.ini_options] markers)
pytestmark = pytest.mark.control_plane
