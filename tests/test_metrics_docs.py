"""Metric-catalog drift gate: hack/verify-metrics-docs.py under tier-1.

Every metric registered in runtime/metrics.py must appear in the
docs/monitoring.md catalog with the right type, and vice versa — a new
metric without a docs row (or a doc row for a deleted metric) fails CI
here, so the catalog cannot rot.
"""

from __future__ import annotations

import importlib.util
import os

import pytest

_SCRIPT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "hack", "verify-metrics-docs.py")


def _load():
    spec = importlib.util.spec_from_file_location("verify_metrics_docs",
                                                  _SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_metrics_and_docs_agree():
    mod = _load()
    assert mod.check() == []


def test_checker_parses_a_plausible_catalog():
    """The drift gate is only as good as its parser: it must actually
    see the registered metrics in the doc tables (an empty parse would
    make test_metrics_and_docs_agree pass vacuously)."""
    mod = _load()
    docs = mod.documented_metrics()
    code = mod.registered_metrics()
    assert len(docs) == len(code) >= 40
    assert docs["tpu_operator_jobs_created_total"] == "counter"
    assert docs["tpu_operator_is_leader"] == "gauge"
    assert docs["tpu_operator_reconcile_duration_seconds"] == "histogram"
    assert "tpu_operator_trace_spans_dropped_total" in docs


def test_checker_reports_drift(tmp_path):
    """A doctored doc (one missing row, one stale row, one wrong type)
    produces exactly the three findings."""
    mod = _load()
    lines = []
    with open(os.path.join(os.path.dirname(os.path.dirname(_SCRIPT)),
                           "docs", "monitoring.md"),
              encoding="utf-8") as f:
        for line in f:
            if "tpu_operator_jobs_created_total" in line:
                continue  # registered but undocumented
            if "tpu_operator_is_leader" in line:
                line = line.replace("| gauge |", "| counter |")
            lines.append(line)
    lines.append("| `tpu_operator_ghost_total` | counter | gone |\n")
    doctored = tmp_path / "monitoring.md"
    doctored.write_text("".join(lines), encoding="utf-8")
    docs = mod.documented_metrics(str(doctored))
    code = mod.registered_metrics()
    assert "tpu_operator_jobs_created_total" in set(code) - set(docs)
    assert "tpu_operator_ghost_total" in set(docs) - set(code)
    assert docs["tpu_operator_is_leader"] == "counter" != \
        code["tpu_operator_is_leader"]


# CI shard (pyproject [tool.pytest.ini_options] markers)
pytestmark = pytest.mark.control_plane
