"""Outlier-rep hardening in bench.py (round-5 verdict #4).

``collect_reps`` replaces stalled reps instead of letting one corrupt
the reported median: BENCH_r05.json shipped a 238 img/s rep against a
2,610 best (spread_frac 0.91) and survived only because the OTHER two
reps agreed. These tests pin the re-run logic with synthetic stalls —
no accelerator involved.
"""

import sys
import os

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from bench import MAX_EXTRA_REPS, SPREAD_THRESHOLD, collect_reps  # noqa: E402


class ScriptedBlock:
    """run_block stand-in yielding a scripted sequence of rep times."""

    def __init__(self, times):
        self.times = list(times)
        self.calls = 0

    def __call__(self) -> float:
        self.calls += 1
        return self.times.pop(0)


def test_stable_reps_run_exactly_base_count():
    block = ScriptedBlock([1.0, 1.01, 0.99])
    times, discarded = collect_reps(block)
    assert block.calls == 3
    assert discarded == []
    assert sorted(times) == [0.99, 1.0, 1.01]


def test_synthetic_stall_is_discarded_and_replaced():
    """One 10x stalled rep (the tunnel-stall shape from BENCH_r05) is
    replaced by a re-run; the stable set carries the honest median and
    the artifact records what was dropped and why."""
    block = ScriptedBlock([1.0, 10.0, 1.02, 0.98])
    times, discarded = collect_reps(block)
    assert block.calls == 4          # one extra rep
    assert sorted(times) == [0.98, 1.0, 1.02]
    assert len(discarded) == 1
    assert discarded[0]["seconds"] == 10.0
    assert "spread_frac" in discarded[0]["cause"]


def test_two_stalls_use_both_extra_reps():
    """Even a majority-stall base round (2 of 3 reps stalled) recovers:
    the stable set is the agreeing subset, not median-anchored."""
    block = ScriptedBlock([1.0, 8.0, 9.0, 1.01, 0.99])
    times, discarded = collect_reps(block)
    assert block.calls == 5
    assert sorted(times) == [0.99, 1.0, 1.01]
    assert {d["seconds"] for d in discarded} == {8.0, 9.0}


def test_extra_reps_are_bounded():
    """A pathologically noisy run stops after MAX_EXTRA_REPS extras and
    reports what it has (spread_frac in the artifact exposes it)."""
    block = ScriptedBlock([1.0, 5.0, 9.0, 7.0, 8.0, 6.0, 4.0])
    times, discarded = collect_reps(block)
    assert block.calls == 3 + MAX_EXTRA_REPS
    assert len(times) == 3
    assert len(discarded) == MAX_EXTRA_REPS


def test_fast_outlier_also_discarded():
    """Outliers in BOTH directions are replaced — a one-off lucky rep
    must not inflate the median any more than a stall may deflate it."""
    block = ScriptedBlock([1.0, 0.1, 1.02, 0.98])
    times, discarded = collect_reps(block)
    assert sorted(times) == [0.98, 1.0, 1.02]
    assert discarded[0]["seconds"] == 0.1


def test_under_threshold_no_rerun():
    # Within the threshold: no extra rep, nothing discarded.
    assert SPREAD_THRESHOLD >= 0.08
    block = ScriptedBlock([1.0, 1.0, 1.08])
    times, discarded = collect_reps(block)
    assert block.calls == 3
    assert discarded == []


def test_environment_fingerprint_fields():
    """The artifact's audit fields (ISSUE 2 satellite): jax version,
    platform/chip kind, python — so round-over-round medians can be
    checked against environment drift."""
    from bench import bench_environment

    env = bench_environment("cpu")
    assert set(env) == {"jax_version", "platform", "chip_kind", "python"}
    import jax

    assert env["jax_version"] == jax.__version__
    assert env["platform"]  # non-empty


def test_config_fingerprint_is_stable_and_config_sensitive():
    from bench import bench_config_fingerprint

    a = bench_config_fingerprint({"batch_size": 256, "stem": "s2d"})
    b = bench_config_fingerprint({"stem": "s2d", "batch_size": 256})
    c = bench_config_fingerprint({"batch_size": 512, "stem": "s2d"})
    assert a == b  # key order irrelevant
    assert a != c  # config drift changes the fingerprint
    assert len(a) == 12
