"""Topology + cluster-spec golden tests (reference pod_test.go
TestClusterSpec and tensorflow_test.go sparse-spec tests)."""

import json

import pytest

from tf_operator_tpu import testutil
from tf_operator_tpu.api import set_defaults
from tf_operator_tpu.bootstrap import (
    build_cluster_spec,
    parse_accelerator,
    render_worker_env,
)
from tf_operator_tpu.bootstrap.cluster import (
    coordinator_address,
    is_distributed,
    process_ranks,
)


# ---------------------------------------------------------------------------
# Topology
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("accel,chips,topo,hosts,devs_per_host", [
    ("v4-8", 4, "2x2x1", 1, 4),
    ("v4-32", 16, "2x2x4", 4, 4),
    ("v5p-8", 4, "2x2x1", 1, 4),
    ("v5p-32", 16, "2x2x4", 4, 4),
    ("v5p-128", 64, "4x4x4", 16, 4),
    ("v5e-4", 4, "2x2", 1, 4),
    ("v5e-8", 8, "2x4", 1, 8),
    ("v5e-16", 16, "4x4", 2, 8),
    ("v6e-64", 64, "8x8", 8, 8),
    ("v3-32", 16, "4x4", 4, 4),
])
def test_parse_accelerator(accel, chips, topo, hosts, devs_per_host):
    t = parse_accelerator(accel)
    assert t.chips == chips
    assert t.topology_str == topo
    assert t.hosts_per_slice == hosts
    assert t.devices_per_host == devs_per_host


def test_explicit_topology_override():
    t = parse_accelerator("v5e-16", topology="2x8")
    assert t.topology == (2, 8)


def test_topology_product_mismatch_rejected():
    with pytest.raises(ValueError, match="topology"):
        parse_accelerator("v5e-16", topology="4x8")


def test_multislice_counts():
    t = parse_accelerator("v5p-32", num_slices=4)
    assert t.num_hosts == 16
    assert t.total_chips == 64


def test_unknown_generation():
    with pytest.raises(ValueError, match="unknown TPU generation"):
        parse_accelerator("v99-8")


# ---------------------------------------------------------------------------
# Cluster spec goldens (reference TestClusterSpec, pod_test.go:230)
# ---------------------------------------------------------------------------

def make_job(**kw):
    job = testutil.new_tpujob(name="test-cluster-spec", **kw)
    set_defaults(job)
    return job


def test_cluster_spec_golden_worker_ps():
    job = make_job(worker=1, ps=2)
    spec = build_cluster_spec(job, "worker", 0, domain="")
    assert json.loads(spec.to_json()) == {
        "cluster": {
            "ps": ["test-cluster-spec-ps-0.default.svc:8470",
                   "test-cluster-spec-ps-1.default.svc:8470"],
            "worker": ["test-cluster-spec-worker-0.default.svc:8470"],
        },
        "task": {"type": "worker", "index": 0},
        "environment": "cloud",
    }


def test_cluster_spec_custom_domain():
    # Reference: EnvCustomClusterDomain variants in TestClusterSpec.
    job = make_job(worker=1)
    spec = build_cluster_spec(job, "worker", 0, domain="cluster.local")
    assert spec.cluster["worker"] == [
        "test-cluster-spec-worker-0.default.svc.cluster.local:8470"]


def test_sparse_cluster_spec_for_elastic_worker():
    # Reference SparseClusterSpec (tensorflow.go:64-83): the worker sees
    # itself + all PS only.
    job = make_job(worker=3, ps=2, chief=1)
    job.spec.enable_elastic_worker = True
    spec = build_cluster_spec(job, "worker", 1, domain="")
    assert set(spec.cluster) == {"ps", "worker"}
    assert spec.cluster["worker"] == ["test-cluster-spec-worker-1.default.svc:8470"]
    assert len(spec.cluster["ps"]) == 2
    # chief still sees the dense view
    dense = build_cluster_spec(job, "chief", 0, domain="")
    assert set(dense.cluster) == {"chief", "ps", "worker"}
    assert len(dense.cluster["worker"]) == 3


def test_custom_port_respected():
    job = make_job(worker=2)
    from tf_operator_tpu.api import constants

    job.spec.replica_specs["worker"].template.spec.containers[0].ports[
        constants.DEFAULT_PORT_NAME] = 9999
    spec = build_cluster_spec(job, "worker", 0, domain="")
    assert spec.cluster["worker"][0].endswith(":9999")


# ---------------------------------------------------------------------------
# Worker env rendering (TF_CONFIG replacement)
# ---------------------------------------------------------------------------

def test_process_ranks_chief_first():
    job = make_job(worker=4, chief=1, ps=2)
    ranks = process_ranks(job)
    assert ranks["chief"] == [0]
    assert ranks["worker"] == [1, 2, 3, 4]
    assert "ps" not in ranks


def test_render_env_golden():
    job = make_job(worker=2, chief=1, accelerator="v5p-32")
    env = render_worker_env(job, "worker", 1, domain="")
    assert env["TPU_ACCELERATOR_TYPE"] == "v5p-32"
    assert env["TPU_TOPOLOGY"] == "2x2x4"
    assert env["JAX_COORDINATOR_ADDRESS"] == \
        "test-cluster-spec-chief-0.default.svc:8476"
    assert env["JAX_NUM_PROCESSES"] == "3"
    assert env["JAX_PROCESS_ID"] == "2"
    # TPU slice membership is worker-scoped (the chief is a coordinator
    # process, not a TPU host): per-slice id, workers-only host list.
    assert env["TPU_WORKER_ID"] == "1"
    assert env["TPU_WORKER_HOSTNAMES"] == (
        "test-cluster-spec-worker-0.default.svc,"
        "test-cluster-spec-worker-1.default.svc")
    chief_env = render_worker_env(job, "chief", 0, domain="")
    assert "TPU_WORKER_ID" not in chief_env
    assert "TPU_WORKER_HOSTNAMES" not in chief_env
    assert chief_env["JAX_PROCESS_ID"] == "0"
    cluster = json.loads(env["TPUJOB_CLUSTER_SPEC"])
    assert cluster["task"] == {"type": "worker", "index": 1}
    assert "MEGASCALE_NUM_SLICES" not in env


def test_render_env_multislice():
    job = make_job(worker=8, accelerator="v5p-32")
    job.spec.slice.num_slices = 2
    env = render_worker_env(job, "worker", 5, domain="")
    assert env["MEGASCALE_NUM_SLICES"] == "2"
    # v5p-32 = 4 hosts/slice; rank 5 -> slice 1
    assert env["MEGASCALE_SLICE_ID"] == "1"
    assert env["MEGASCALE_COORDINATOR_ADDRESS"] == env["JAX_COORDINATOR_ADDRESS"]


def test_render_env_multislice_with_chief_offset():
    # Regression: slice id comes from the worker index, not the global rank
    # (a chief offsets ranks by one but is not a slice host).
    job = make_job(worker=8, chief=1, accelerator="v5p-32")
    job.spec.slice.num_slices = 2
    env = render_worker_env(job, "worker", 3, domain="")
    assert env["JAX_PROCESS_ID"] == "4"  # chief is rank 0
    assert env["MEGASCALE_SLICE_ID"] == "0"  # worker 3 is in slice 0
    env7 = render_worker_env(job, "worker", 7, domain="")
    assert env7["MEGASCALE_SLICE_ID"] == "1"


def test_out_of_range_index_gets_unique_rank():
    # Elastic scale-up transient: index beyond spec.replicas must not
    # collide with an existing process id.
    job = make_job(worker=2, chief=1)
    env = render_worker_env(job, "worker", 2, domain="")
    assert env["JAX_PROCESS_ID"] == "3"
    assert int(env["JAX_NUM_PROCESSES"]) >= 4


def test_out_of_range_render_references_no_nonexistent_pods():
    """Elastic-grow transient (bootstrap/cluster.py): a worker rendered
    with an index beyond spec.replicas must see a cluster view made of
    pods that EXIST — the declared replicas plus itself — and never a
    hostname for an index between replicas and its own (those pods
    have not been created yet, so a worker handed them would dial
    hosts that do not resolve)."""
    job = make_job(worker=2, accelerator="v5e-16")  # 2 hosts/slice
    env = render_worker_env(job, "worker", 5, domain="")
    # Slice window for index 5 is workers 4..5; workers 2..4 do not
    # exist — only the pod's own name may appear.
    assert env["TPU_WORKER_HOSTNAMES"] == \
        "test-cluster-spec-worker-5.default.svc"
    cluster = json.loads(env["TPUJOB_CLUSTER_SPEC"])
    workers = cluster["cluster"]["worker"]
    # The view holds the declared replicas plus the rendered pod
    # itself, and nothing in between.
    assert workers == [
        "test-cluster-spec-worker-0.default.svc:8470",
        "test-cluster-spec-worker-1.default.svc:8470",
        "test-cluster-spec-worker-5.default.svc:8470",
    ]
    for missing in (2, 3, 4):
        assert f"worker-{missing}" not in env["TPUJOB_CLUSTER_SPEC"]
        assert f"worker-{missing}" not in env["TPU_WORKER_HOSTNAMES"]
    # Rank identity stays unique and in range (the pre-existing pin).
    assert env["JAX_PROCESS_ID"] == "5"
    assert int(env["JAX_NUM_PROCESSES"]) >= 6


def test_single_process_job_gets_no_cluster_env():
    # Reference isDistributed (pod.go:296-317): single-process jobs get no
    # TF_CONFIG; here no JAX_*/cluster-spec env.
    job = make_job(worker=1, accelerator="v5e-4")
    assert not is_distributed(job)
    env = render_worker_env(job, "worker", 0, domain="")
    assert "JAX_COORDINATOR_ADDRESS" not in env
    assert "TPUJOB_CLUSTER_SPEC" not in env
    assert env["TPU_ACCELERATOR_TYPE"] == "v5e-4"


def test_coordinator_is_worker0_when_chiefless():
    job = make_job(worker=2)
    assert coordinator_address(job, domain="") == \
        "test-cluster-spec-worker-0.default.svc:8476"


def test_ps_gets_cluster_spec_but_no_jax_rank():
    job = make_job(worker=2, ps=1)
    env = render_worker_env(job, "ps", 0, domain="")
    assert "TPUJOB_CLUSTER_SPEC" in env
    assert "JAX_PROCESS_ID" not in env


def test_multislice_per_slice_worker_env():
    # Round-2 hardening: TPU_WORKER_ID / TPU_WORKER_HOSTNAMES are scoped
    # to the slice (libtpu semantics), while JAX_* stay global.
    job = make_job(worker=8, accelerator="v5p-32")
    job.spec.slice.num_slices = 2
    env = render_worker_env(job, "worker", 5, domain="")
    # v5p-32 = 4 hosts/slice; worker 5 = slice 1, in-slice id 1.
    assert env["MEGASCALE_SLICE_ID"] == "1"
    assert env["TPU_WORKER_ID"] == "1"
    hosts = env["TPU_WORKER_HOSTNAMES"].split(",")
    assert [h.split(".")[0] for h in hosts] == [
        f"{job.metadata.name}-worker-{i}" for i in (4, 5, 6, 7)]
    # Global jax.distributed view is unchanged.
    assert env["JAX_PROCESS_ID"] == "5"
    assert env["JAX_NUM_PROCESSES"] == "8"
    # Slice coordinator = first worker of THIS slice.
    assert env["MEGASCALE_SLICE_COORDINATOR"].startswith(
        f"{job.metadata.name}-worker-4.")


def test_multislice_chief_is_not_a_slice_host():
    job = make_job(worker=8, chief=1, accelerator="v5p-32")
    job.spec.slice.num_slices = 2
    env = render_worker_env(job, "chief", 0, domain="")
    # The chief coordinates jax.distributed globally...
    assert env["JAX_PROCESS_ID"] == "0"
    assert env["MEGASCALE_NUM_SLICES"] == "2"
    # ...but must not claim TPU slice membership.
    assert "TPU_WORKER_ID" not in env
    assert "TPU_WORKER_HOSTNAMES" not in env
    assert "MEGASCALE_SLICE_ID" not in env
    # Workers keep per-slice ids regardless of the chief's rank offset.
    wenv = render_worker_env(job, "worker", 4, domain="")
    assert (wenv["TPU_WORKER_ID"], wenv["MEGASCALE_SLICE_ID"]) == ("0", "1")
    assert wenv["JAX_PROCESS_ID"] == "5"  # chief is global rank 0


def test_single_slice_worker_scoped_tpu_env():
    # num_slices == 1 with an accelerator: same worker-scoped slice
    # semantics as multislice, just without the MEGASCALE_* layer.
    job = make_job(worker=2, chief=1, accelerator="v5p-32")
    env = render_worker_env(job, "worker", 1, domain="")
    assert env["TPU_WORKER_ID"] == "1"
    assert "chief" not in env["TPU_WORKER_HOSTNAMES"]
    assert "MEGASCALE_NUM_SLICES" not in env


def test_no_accelerator_keeps_legacy_global_worker_env():
    # Plain process jobs (no TPU slice declared) keep rank-based ids and
    # the full ranked host list — the local-runtime contract.
    job = make_job(worker=2, chief=1)
    env = render_worker_env(job, "worker", 1, domain="")
    assert env["TPU_WORKER_ID"] == "2"
    assert len(env["TPU_WORKER_HOSTNAMES"].split(",")) == 3


def test_validation_warnings_multislice_shape():
    from tf_operator_tpu.api.validation import validation_warnings

    job = make_job(worker=6, ps=2, accelerator="v5p-32")
    job.spec.slice.num_slices = 2  # wants 8 workers, spec has 6
    warnings = validation_warnings(job)
    assert any("under- or over-subscribed" in w for w in warnings)
    # ps no longer warns: train/ps.py is a real runtime (round 4).
    assert not any("parameter-server" in w for w in warnings)
    # A well-shaped job warns about nothing.
    ok = make_job(worker=8, accelerator="v5p-32")
    ok.spec.slice.num_slices = 2
    assert validation_warnings(ok) == []

def test_multislice_resize_rerenders_megascale_env():
    """Round-5 multislice elasticity golden: resizing numSlices (the
    dcn axis) re-renders the per-slice MEGASCALE env for the new world
    — slice membership, per-slice coordinators, and slice count all
    follow the resize — and the bootstrap digest changes for every
    worker (dense AND sparse-elastic: MEGASCALE_NUM_SLICES is a world
    fact even sparse workers join), so the engine world-restarts them
    onto the new slicing (reference enableDynamicWorker taken to the
    multislice case, types.go:66-67)."""
    from tf_operator_tpu.controller.tpu_controller import (
        TPUJobController,
    )
    from tf_operator_tpu.runtime.store import Store

    plugin = TPUJobController(Store())

    def job_with_slices(n_slices, workers):
        job = make_job(worker=workers)
        job.spec.slice.accelerator = "v5e-16"  # 2 hosts per slice
        job.spec.slice.num_slices = n_slices
        return job

    before = job_with_slices(2, 4)
    after = job_with_slices(4, 8)

    env_b = render_worker_env(before, "worker", 3, domain="")
    assert env_b["MEGASCALE_NUM_SLICES"] == "2"
    assert env_b["MEGASCALE_SLICE_ID"] == "1"
    env_a = render_worker_env(after, "worker", 3, domain="")
    assert env_a["MEGASCALE_NUM_SLICES"] == "4"
    assert env_a["MEGASCALE_SLICE_ID"] == "1"
    # Worker 6 lands in a slice that did not exist before the resize,
    # with a per-slice coordinator rendered for the new world.
    env_new = render_worker_env(after, "worker", 6, domain="")
    assert env_new["MEGASCALE_SLICE_ID"] == "3"
    assert env_new["MEGASCALE_SLICE_COORDINATOR"].startswith(
        "test-cluster-spec-worker-6.")
    assert env_new["JAX_NUM_PROCESSES"] == "8"

    # Digest flip drives the engine's restart-from-checkpoint path.
    assert (plugin.bootstrap_hash(before, "worker", 0)
            != plugin.bootstrap_hash(after, "worker", 0))
    # Sparse-elastic workers restart too: the slice count is part of
    # the world they rendezvous with over DCN.
    before.spec.enable_elastic_worker = True
    after.spec.enable_elastic_worker = True
    assert (plugin.bootstrap_hash(before, "worker", 0)
            != plugin.bootstrap_hash(after, "worker", 0))


# CI shard (pyproject [tool.pytest.ini_options] markers)
pytestmark = pytest.mark.control_plane
