"""Node-agent plane (runtime/nodeagent.py) + the kube flags it lifts.

The agent is the DaemonSet analog that closes the process-supervision
gap on --backend kube (docs/node-agent.md): it relays preemption
notices (pod annotation -> TPUJOB_PREEMPT_FILE), mirrors worker
checkpoint state (TPUJOB_CKPT_FILE -> ckpt-state annotation), and
heartbeats its Node so the operator knows which gangs are
barrier-capable. These tests pin:

- the relay contract against the hermetic fake apiserver (notice file,
  ckpt mirror, cleanup, node scoping, heartbeats);
- bind validation: the fake 422s placements a real kubelet would
  reject (taints / nodeSelector / cpu fit), and the in-operator binder
  never proposes one;
- the lifted-flag e2e arcs: drain mid-train resolves the save barrier
  through the agent relay with restoredFromStep == lastCheckpointStep,
  tenant-queue reclaim evicts a borrower on kube, a serving gang rides
  a drain with its spool intact, and the no-agent control degrades to
  plain eviction (flag semantics identical to agentless today);
- the CLI accepting --enable-tenant-queues / --enable-ckpt-coordination
  / --enable-serving with --backend kube.
"""

from __future__ import annotations

import json
import os
import time

import pytest

from tf_operator_tpu.api import constants
from tf_operator_tpu.api.types import (
    CheckpointPolicy,
    Container,
    HealthPolicy,
    JobConditionType,
    ObjectMeta,
    PodSpec,
    PodTemplateSpec,
    ReplicaSpec,
    RestartPolicy,
    RunPolicy,
    ServingPolicy,
    Toleration,
    TPUJob,
    TPUJobSpec,
    TPUSliceSpec,
)
from tf_operator_tpu.controller.ckpt import JOB_CKPT_BARRIER_SAVED_REASON
from tf_operator_tpu.runtime import store as store_mod
from tf_operator_tpu.runtime.events import (
    REASON_CKPT_BARRIER_REQUESTED,
    REASON_CKPT_BARRIER_SAVED,
)
from tf_operator_tpu.runtime.kube import (
    KubeApiError,
    KubeClient,
    KubeConfig,
    KubeOperator,
    node_from_k8s,
    tpujob_to_k8s,
)
from tf_operator_tpu.runtime.kube_fake import FakeKubeApiServer
from tf_operator_tpu.runtime.nodeagent import KubeNodeAgent

pytestmark = pytest.mark.control_plane


def wait_for(cond, timeout=25.0, interval=0.05, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        result = cond()
        if result:
            return result
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture
def fake():
    with FakeKubeApiServer() as server:
        yield server


@pytest.fixture
def client(fake):
    return KubeClient(KubeConfig(server=fake.url))


def make_agent(fake, node, relay_dir, **kw):
    kw.setdefault("heartbeat_seconds", 1.0)
    kw.setdefault("ckpt_poll_seconds", 0.05)
    return KubeNodeAgent(KubeClient(KubeConfig(server=fake.url)), node,
                         str(relay_dir), **kw)


def raw_pod(name, node="", relay_dir="", token="tok1", annotations=None,
            resources=None, node_selector=None, tolerations=None,
            ns="default"):
    """A plain (non-job) pod in wire form, optionally relay-wired."""
    ann = dict(annotations or {})
    if relay_dir:
        ann.setdefault(constants.ANNOTATION_RELAY_TOKEN, token)
    d = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {"name": name, "namespace": ns, "annotations": ann},
        "spec": {
            "containers": [{"name": constants.DEFAULT_CONTAINER_NAME,
                            "image": "w:latest",
                            "command": ["sleep", "1"]}],
            "restartPolicy": "Never",
        },
    }
    if node:
        d["spec"]["nodeName"] = node
    if relay_dir:
        d["spec"]["volumes"] = [{
            "name": "tpu-operator-relay",
            "hostPath": {"path": str(relay_dir),
                         "type": "DirectoryOrCreate"}}]
        d["spec"]["containers"][0]["volumeMounts"] = [{
            "name": "tpu-operator-relay", "mountPath": str(relay_dir)}]
    if resources:
        d["spec"]["containers"][0]["resources"] = {"limits": dict(resources)}
    if node_selector:
        d["spec"]["nodeSelector"] = dict(node_selector)
    if tolerations:
        d["spec"]["tolerations"] = list(tolerations)
    return d


def env_of(fake, ns, name):
    pod = fake.state.objects["pods"].get((ns, name)) or {}
    cont = ((pod.get("spec") or {}).get("containers") or [{}])[0]
    return {e["name"]: e.get("value", "") for e in cont.get("env") or []}


def annotations_of(fake, ns, name):
    pod = fake.state.objects["pods"].get((ns, name)) or {}
    return (pod.get("metadata") or {}).get("annotations") or {}


def _node_of(fake, ns, name):
    pod = fake.state.objects["pods"].get((ns, name))
    return ((pod or {}).get("spec") or {}).get("nodeName", "")


def _pod_uid(fake, ns, name):
    pod = fake.state.objects["pods"].get((ns, name))
    return ((pod or {}).get("metadata") or {}).get("uid", "")


def _atomic_write(path, payload):
    with open(path + ".tmp", "w", encoding="utf-8") as f:
        json.dump(payload, f)
    os.replace(path + ".tmp", path)


def relay_paths(fake, base_dir, ns, name):
    """(preempt, ckpt) paths for a pod as the relay module renders them."""
    from tf_operator_tpu.runtime import relay as relay_mod
    from tf_operator_tpu.runtime.kube import pod_from_k8s

    pod = pod_from_k8s(fake.state.objects["pods"][(ns, name)])
    return (relay_mod.preempt_path(str(base_dir), pod),
            relay_mod.ckpt_path(str(base_dir), pod))


def kube_ckpt_job(name, ckpt_dir, workers=2, queue="", serving=False,
                  spool=""):
    """Wire-form TPUJob: v5e-8 per slice, one replica per slice, opted
    into health drains + coordinated checkpoints (interval_steps huge so
    the barrier save is the ONLY save — keeps restoredFromStep ==
    lastCheckpointStep race-free)."""
    job = TPUJob(metadata=ObjectMeta(name=name, namespace="default"))
    template = PodTemplateSpec(spec=PodSpec(containers=[
        Container(name=constants.DEFAULT_CONTAINER_NAME,
                  image="tpu-worker:latest",
                  command=["python", "-m", "train"])]))
    rtype = "serving" if serving else "worker"
    run_policy = RunPolicy(
        health_policy=HealthPolicy(enabled=True),
        checkpoint_policy=CheckpointPolicy(
            enabled=True, directory=ckpt_dir, interval_steps=100000,
            barrier_timeout_seconds=20.0))
    if serving:
        run_policy.serving_policy = ServingPolicy(
            enabled=True, spool_directory=spool)
    job.spec = TPUJobSpec(
        replica_specs={rtype: ReplicaSpec(
            replicas=workers, template=template,
            restart_policy=RestartPolicy.NEVER)},
        run_policy=run_policy,
        slice=TPUSliceSpec(accelerator="v5e-8", num_slices=workers),
        queue_name=queue)
    return tpujob_to_k8s(job)


def kube_plain_job(name, workers, queue=""):
    job = TPUJob(metadata=ObjectMeta(name=name, namespace="default"))
    template = PodTemplateSpec(spec=PodSpec(containers=[
        Container(name=constants.DEFAULT_CONTAINER_NAME,
                  image="tpu-worker:latest",
                  command=["python", "-m", "train"])]))
    job.spec = TPUJobSpec(
        replica_specs={"worker": ReplicaSpec(
            replicas=workers, template=template,
            restart_policy=RestartPolicy.NEVER)},
        slice=TPUSliceSpec(accelerator="v5e-8", num_slices=workers),
        queue_name=queue)
    return tpujob_to_k8s(job)


# ---------------------------------------------------------------------------
# Relay contract: one agent, one node, raw pods
# ---------------------------------------------------------------------------


class TestNodeAgentRelay:
    def test_requires_node_name(self, client, tmp_path):
        with pytest.raises(ValueError):
            KubeNodeAgent(client, "", str(tmp_path))

    def test_heartbeat_lands_and_parses(self, fake, client, tmp_path):
        fake.state.add_node("n1", chips=8)
        agent = make_agent(fake, "n1", tmp_path, heartbeat_seconds=0.2)
        agent.start()
        try:
            def beat():
                raw = fake.state.objects["nodes"].get(("", "n1")) or {}
                ann = (raw.get("metadata") or {}).get("annotations") or {}
                return ann.get(constants.ANNOTATION_AGENT_HEARTBEAT)
            stamp = wait_for(beat, msg="heartbeat annotation")
            # The informer-side parser must read it back as a timestamp
            # (this is what _barrier_capable consumes).
            node = node_from_k8s(fake.state.objects["nodes"][("", "n1")])
            assert node.status.last_heartbeat is not None
            # And it keeps beating: a later stamp supersedes.
            wait_for(lambda: beat() != stamp, msg="second heartbeat")
        finally:
            agent.stop()

    def test_notice_annotation_becomes_preempt_file(self, fake, client,
                                                    tmp_path):
        fake.state.add_node("n1", chips=8)
        fake.state.create("pods", "default",
                          raw_pod("p1", node="n1", relay_dir=tmp_path))
        agent = make_agent(fake, "n1", tmp_path)
        agent.start()
        try:
            notice = {"barrier": "b1", "deadline": 123.0,
                      "reason": "maintenance"}
            client.patch(store_mod.PODS, "default", "p1", {"metadata": {
                "annotations": {constants.ANNOTATION_PREEMPT_NOTICE:
                                json.dumps(notice, sort_keys=True)}}})
            path, _ = relay_paths(fake, tmp_path, "default", "p1")
            wait_for(lambda: os.path.exists(path), msg="preempt file")
            with open(path, encoding="utf-8") as f:
                assert json.load(f) == notice
            # An updated notice rewrites the file.
            notice2 = dict(notice, barrier="b2")
            client.patch(store_mod.PODS, "default", "p1", {"metadata": {
                "annotations": {constants.ANNOTATION_PREEMPT_NOTICE:
                                json.dumps(notice2, sort_keys=True)}}})

            def updated():
                with open(path, encoding="utf-8") as f:
                    return json.load(f).get("barrier") == "b2"
            wait_for(updated, msg="notice rewrite")
        finally:
            agent.stop()

    def test_ckpt_file_mirrors_to_annotation(self, fake, client, tmp_path):
        fake.state.add_node("n1", chips=8)
        fake.state.create("pods", "default",
                          raw_pod("p1", node="n1", relay_dir=tmp_path))
        agent = make_agent(fake, "n1", tmp_path)
        agent.start()
        try:
            _, path = relay_paths(fake, tmp_path, "default", "p1")
            payload = {"step": 3, "barrier": "b1"}
            _atomic_write(path, payload)
            wait_for(lambda: annotations_of(fake, "default", "p1").get(
                constants.ANNOTATION_CKPT_STATE), msg="ckpt-state annotation")
            mirrored = annotations_of(fake, "default", "p1")[
                constants.ANNOTATION_CKPT_STATE]
            assert json.loads(mirrored) == payload
        finally:
            agent.stop()

    def test_pod_delete_cleans_relay_files(self, fake, client, tmp_path):
        fake.state.add_node("n1", chips=8)
        fake.state.create("pods", "default",
                          raw_pod("p1", node="n1", relay_dir=tmp_path))
        agent = make_agent(fake, "n1", tmp_path)
        agent.start()
        try:
            ppath, cpath = relay_paths(fake, tmp_path, "default", "p1")
            client.patch(store_mod.PODS, "default", "p1", {"metadata": {
                "annotations": {constants.ANNOTATION_PREEMPT_NOTICE:
                                json.dumps({"barrier": "b1"})}}})
            _atomic_write(cpath, {"step": 1})
            wait_for(lambda: os.path.exists(ppath), msg="preempt file")
            client.delete(store_mod.PODS, "default", "p1")
            wait_for(lambda: not os.path.exists(ppath)
                     and not os.path.exists(cpath),
                     msg="relay files unlinked on delete")
        finally:
            agent.stop()

    def test_ignores_pods_on_other_nodes(self, fake, client, tmp_path):
        fake.state.add_node("n1", chips=8)
        fake.state.add_node("n2", chips=8)
        fake.state.create(
            "pods", "default",
            raw_pod("p2", node="n2", relay_dir=tmp_path,
                    annotations={constants.ANNOTATION_PREEMPT_NOTICE:
                                 json.dumps({"barrier": "bx"})}))
        agent = make_agent(fake, "n1", tmp_path)  # agent for n1, pod on n2
        agent.start()
        try:
            time.sleep(0.6)
            path, _ = relay_paths(fake, tmp_path, "default", "p2")
            assert not os.path.exists(path)
        finally:
            agent.stop()


# ---------------------------------------------------------------------------
# Bind validation: the fake rejects what a kubelet would reject
# ---------------------------------------------------------------------------


class TestFakeBindValidation:
    def test_taint_without_toleration_is_422(self, fake, client):
        fake.state.add_node("t1", chips=8, taints=[
            {"key": "dedicated", "value": "ml", "effect": "NoSchedule"}])
        fake.state.create("pods", "default", raw_pod("p1"))
        with pytest.raises(KubeApiError) as err:
            client.bind_pod("default", "p1", "t1")
        assert err.value.code == 422

    def test_matching_toleration_binds(self, fake, client):
        fake.state.add_node("t1", chips=8, taints=[
            {"key": "dedicated", "value": "ml", "effect": "NoSchedule"}])
        fake.state.create("pods", "default", raw_pod(
            "p1", tolerations=[{"key": "dedicated", "operator": "Equal",
                                "value": "ml", "effect": "NoSchedule"}]))
        client.bind_pod("default", "p1", "t1")
        assert _node_of(fake, "default", "p1") == "t1"

    def test_node_selector_mismatch_is_422(self, fake, client):
        fake.state.add_node("n1", chips=8, labels={"pool": "cpu"})
        fake.state.create("pods", "default", raw_pod(
            "p1", node_selector={"pool": "tpu"}))
        with pytest.raises(KubeApiError) as err:
            client.bind_pod("default", "p1", "n1")
        assert err.value.code == 422
        # ... and a matching label set binds.
        fake.state.add_node("n2", chips=8, labels={"pool": "tpu"})
        client.bind_pod("default", "p1", "n2")
        assert _node_of(fake, "default", "p1") == "n2"

    def test_cpu_overcommit_is_422(self, fake, client):
        fake.state.add_node("n1", chips=8, cpu="1")
        fake.state.create("pods", "default",
                          raw_pod("p1", resources={"cpu": "600m"}))
        fake.state.create("pods", "default",
                          raw_pod("p2", resources={"cpu": "600m"}))
        client.bind_pod("default", "p1", "n1")
        with pytest.raises(KubeApiError) as err:
            client.bind_pod("default", "p2", "n1")
        assert err.value.code == 422

    def test_unreported_allocatable_skips_fit(self, fake, client):
        # A node that reports no cpu/memory must not reject on fit.
        fake.state.add_node("n1", chips=8)
        fake.state.create("pods", "default",
                          raw_pod("p1", resources={"cpu": "64",
                                                   "memory": "1Ti"}))
        client.bind_pod("default", "p1", "n1")
        assert _node_of(fake, "default", "p1") == "n1"


@pytest.mark.e2e
class TestBinderHonorsNodeInventory:
    def test_binder_avoids_tainted_node(self, fake, client):
        """Two candidate nodes, one carrying a NoSchedule taint the
        worker does not tolerate: the gang binder must place on the
        clean one (a taint miss would 422 at the fake and the pod
        would never bind)."""
        fake.state.add_node("dom-a-n0", chips=8, ici_domain="dom-a",
                            taints=[{"key": "dedicated", "value": "infra",
                                     "effect": "NoSchedule"}])
        fake.state.add_node("dom-b-n0", chips=8, ici_domain="dom-b")
        op = KubeOperator(client, post_events=False,
                          enable_gang_scheduling=True)
        op.start(threadiness=1, sync_timeout=10)
        try:
            fake.state.create(constants.PLURAL, "default",
                              kube_plain_job("tj", workers=1))
            node = wait_for(
                lambda: _node_of(fake, "default", "tj-worker-0"),
                msg="worker bound")
            assert node == "dom-b-n0"
        finally:
            op.stop()


# ---------------------------------------------------------------------------
# E2E: drain mid-train rides the agent relay end to end
# ---------------------------------------------------------------------------


def _cluster(fake, domains=("dom-a", "dom-b", "dom-c")):
    for dom in domains:
        fake.state.add_node(f"{dom}-n0", chips=8, ici_domain=dom)
    return [f"{dom}-n0" for dom in domains]


def _start_agents(fake, relay_dir, nodes):
    agents = []
    for n in nodes:
        a = make_agent(fake, n, relay_dir)
        a.start()
        agents.append(a)
    return agents


@pytest.mark.e2e
class TestCkptDrainE2E:
    def test_drain_resolves_barrier_and_restores(self, fake, client,
                                                 tmp_path):
        """Maintenance on a worker's node: notice reaches the worker's
        TPUJOB_PREEMPT_FILE through its node agent, the final-save acks
        flow back through TPUJOB_CKPT_FILE, the gang drains only after
        the barrier resolves, and the rebound pods restore from exactly
        the step the barrier committed."""
        relay_dir = tmp_path / "relay"
        relay_dir.mkdir()
        nodes = _cluster(fake)
        op = KubeOperator(client, post_events=False,
                          enable_gang_scheduling=True,
                          enable_ckpt_coordination=True,
                          relay_dir=str(relay_dir))
        op.start(threadiness=1, sync_timeout=10)
        agents = _start_agents(fake, relay_dir, nodes)
        names = ["cj-worker-0", "cj-worker-1"]
        try:
            fake.state.create(constants.PLURAL, "default",
                              kube_ckpt_job("cj", str(tmp_path / "ckpt")))
            wait_for(lambda: all(_node_of(fake, "default", n)
                                 for n in names), msg="gang bound")
            fake.state.set_all_pods_phase(
                "default", "Running",
                selector={constants.LABEL_JOB_NAME: "cj"})
            old_uids = {n: _pod_uid(fake, "default", n) for n in names}
            old_envs = {n: env_of(fake, "default", n) for n in names}
            for n in names:  # relay env rendered at create time
                assert old_envs[n][constants.ENV_PREEMPT_FILE]
                assert old_envs[n][constants.ENV_CKPT_FILE]

            victim = _node_of(fake, "default", "cj-worker-0")
            fake.state.inject_maintenance(victim)

            # 1. The barrier notice lands in every worker's preempt file.
            def notices():
                out = {}
                for n in names:
                    path = old_envs[n][constants.ENV_PREEMPT_FILE]
                    if not os.path.exists(path):
                        return None
                    with open(path, encoding="utf-8") as f:
                        out[n] = json.load(f)
                return out
            got = wait_for(notices, msg="preemption notices relayed")
            barrier = got["cj-worker-0"]["barrier"]
            assert barrier
            assert got["cj-worker-1"]["barrier"] == barrier
            assert "deadline" in got["cj-worker-0"]
            # The drain is gated: pods still alive while unacked.
            assert _pod_uid(fake, "default", "cj-worker-0") == \
                old_uids["cj-worker-0"]

            # 2. Workers ack with their final save.
            for n in names:
                _atomic_write(old_envs[n][constants.ENV_CKPT_FILE],
                              {"step": 5, "progress_step": 7,
                               "barrier": barrier,
                               "directory": str(tmp_path / "ckpt"),
                               "save_seconds": 0.1})

            # 3. Barrier resolves -> atomic drain -> rebind off victim.
            def rebound():
                for n in names:
                    node = _node_of(fake, "default", n)
                    if (not node or node == victim
                            or _pod_uid(fake, "default", n) == old_uids[n]):
                        return False
                return True
            wait_for(rebound, timeout=30, msg="gang rebound off victim")

            # 4. Restore-with-identity: fresh incarnation, fresh relay
            #    token, committed step in env.
            for n in names:
                env = env_of(fake, "default", n)
                assert env[constants.ENV_RESTORE_STEP] == "5"
                assert env[constants.ENV_CKPT_FILE] != \
                    old_envs[n][constants.ENV_CKPT_FILE]
            fake.state.set_all_pods_phase(
                "default", "Running",
                selector={constants.LABEL_JOB_NAME: "cj"})

            # 5. A rebound worker confirms its restore over the relay...
            env0 = env_of(fake, "default", "cj-worker-0")
            _atomic_write(env0[constants.ENV_CKPT_FILE],
                          {"step": 5, "restored_from_step": 5})

            # ...and the job status closes the loop.
            def status():
                raw = client.get(store_mod.TPUJOBS, "default", "cj")
                st = raw.get("status") or {}
                return st if st.get("restoredFromStep") is not None else None
            st = wait_for(status, msg="restoredFromStep on job status")
            assert st["lastCheckpointStep"] == 5
            assert st["restoredFromStep"] == st["lastCheckpointStep"]
            conds = [c for c in st.get("conditions") or []
                     if c.get("type") == JobConditionType.CHECKPOINT_BARRIER]
            assert conds and conds[0].get("status") == "False"
            assert conds[0].get("reason") == JOB_CKPT_BARRIER_SAVED_REASON
            reasons = {e.reason for e in op.controller.recorder.events}
            assert REASON_CKPT_BARRIER_SAVED in reasons
        finally:
            for a in agents:
                a.stop()
            op.stop()

    def test_no_agent_heartbeat_degrades_to_plain_eviction(
            self, fake, client, tmp_path):
        """No agents running: the gang is not barrier-capable, so a
        drain must evict immediately — never hang on acks that cannot
        arrive — and no relay artifacts may appear (flag-on behavior
        with a dead agent == flag-off behavior)."""
        relay_dir = tmp_path / "relay"
        relay_dir.mkdir()
        _cluster(fake)
        op = KubeOperator(client, post_events=False,
                          enable_gang_scheduling=True,
                          enable_ckpt_coordination=True,
                          relay_dir=str(relay_dir))
        op.start(threadiness=1, sync_timeout=10)
        names = ["nj-worker-0", "nj-worker-1"]
        try:
            fake.state.create(constants.PLURAL, "default",
                              kube_ckpt_job("nj", str(tmp_path / "ckpt")))
            wait_for(lambda: all(_node_of(fake, "default", n)
                                 for n in names), msg="gang bound")
            fake.state.set_all_pods_phase(
                "default", "Running",
                selector={constants.LABEL_JOB_NAME: "nj"})
            old_uids = {n: _pod_uid(fake, "default", n) for n in names}
            victim = _node_of(fake, "default", "nj-worker-0")
            fake.state.inject_maintenance(victim)

            def rebound():
                for n in names:
                    node = _node_of(fake, "default", n)
                    if (not node or node == victim
                            or _pod_uid(fake, "default", n) == old_uids[n]):
                        return False
                return True
            wait_for(rebound, timeout=30, msg="plain drain rebound")

            assert os.listdir(relay_dir) == []
            reasons = {e.reason for e in op.controller.recorder.events}
            assert REASON_CKPT_BARRIER_REQUESTED not in reasons
            for n in names:
                assert constants.ANNOTATION_PREEMPT_NOTICE not in \
                    annotations_of(fake, "default", n)
                assert constants.ENV_RESTORE_STEP not in \
                    env_of(fake, "default", n)
        finally:
            op.stop()


# ---------------------------------------------------------------------------
# E2E: tenant-queue reclaim on kube
# ---------------------------------------------------------------------------


QUEUE_YAML = """\
clusterQueues:
  - name: cq-a
    nominalChips: 8
    cohort: pool
  - name: cq-b
    nominalChips: 8
    cohort: pool
tenantQueues:
  - name: team-a
    clusterQueue: cq-a
  - name: team-b
    clusterQueue: cq-b
"""


@pytest.mark.e2e
class TestTenantReclaimE2E:
    def test_reclaim_evicts_borrower(self, fake, client, tmp_path):
        """team-b borrows cq-a's idle nominal to run 16 chips; when a
        team-a job shows up, reclaim displaces the borrower's gang (its
        bound pods are deleted; the engine's replacements queue unbound
        because borrowing is frozen) and the owner binds."""
        qcfg = tmp_path / "queues.yaml"
        qcfg.write_text(QUEUE_YAML, encoding="utf-8")
        fake.state.add_node("dom-a-n0", chips=8, ici_domain="dom-a")
        fake.state.add_node("dom-b-n0", chips=8, ici_domain="dom-b")
        op = KubeOperator(client, post_events=False,
                          enable_gang_scheduling=True,
                          enable_tenant_queues=True,
                          queue_config=str(qcfg))
        op.start(threadiness=1, sync_timeout=10)
        borrower = ["bj-worker-0", "bj-worker-1"]
        try:
            fake.state.create(constants.PLURAL, "default",
                              kube_plain_job("bj", workers=2,
                                             queue="team-b"))
            wait_for(lambda: all(_node_of(fake, "default", n)
                                 for n in borrower),
                     msg="borrower bound via cohort borrowing")
            fake.state.set_all_pods_phase(
                "default", "Running",
                selector={constants.LABEL_JOB_NAME: "bj"})
            old_uids = {n: _pod_uid(fake, "default", n) for n in borrower}

            fake.state.create(constants.PLURAL, "default",
                              kube_plain_job("aj", workers=1,
                                             queue="team-a"))
            # Reclaim: the borrower's bound incarnations are evicted
            # (any replacement pod is a fresh, unbound incarnation —
            # its borrowing is frozen while the nominal demand is
            # unmet), and the owner binds onto the freed chips.
            def borrower_evicted():
                for n in borrower:
                    uid = _pod_uid(fake, "default", n)
                    if uid == old_uids[n] or _node_of(fake, "default", n):
                        return False
                return True
            wait_for(borrower_evicted, timeout=30,
                     msg="borrower evicted by reclaim")
            node = wait_for(
                lambda: _node_of(fake, "default", "aj-worker-0"),
                timeout=30, msg="owner bound after reclaim")
            assert node
            reasons = {e.reason for e in op.controller.recorder.events}
            assert "QuotaReclaimed" in reasons
        finally:
            op.stop()


# ---------------------------------------------------------------------------
# E2E: serving gang rides a drain, spool intact
# ---------------------------------------------------------------------------


@pytest.mark.e2e
class TestServingDrainE2E:
    def test_serving_gang_survives_drain_with_spool_intact(
            self, fake, client, tmp_path):
        """Serving replicas gate the barrier like workers (their ack is
        'requests re-spooled'); after the drain the gang is rebound off
        the victim and every pending request file is still there —
        nothing in flight was dropped at the spool."""
        relay_dir = tmp_path / "relay"
        relay_dir.mkdir()
        spool = tmp_path / "spool"
        (spool / "pending").mkdir(parents=True)
        nodes = _cluster(fake)
        op = KubeOperator(client, post_events=False,
                          enable_gang_scheduling=True,
                          enable_ckpt_coordination=True,
                          enable_serving=True,
                          relay_dir=str(relay_dir))
        op.start(threadiness=1, sync_timeout=10)
        agents = _start_agents(fake, relay_dir, nodes)
        names = ["sj-serving-0", "sj-serving-1"]
        try:
            fake.state.create(
                constants.PLURAL, "default",
                kube_ckpt_job("sj", str(spool), serving=True,
                              spool=str(spool)))
            wait_for(lambda: all(_node_of(fake, "default", n)
                                 for n in names), msg="serving gang bound")
            fake.state.set_all_pods_phase(
                "default", "Running",
                selector={constants.LABEL_JOB_NAME: "sj"})
            old_envs = {n: env_of(fake, "default", n) for n in names}
            old_uids = {n: _pod_uid(fake, "default", n) for n in names}
            for n in names:
                assert old_envs[n][constants.ENV_SERVE_SPOOL] == str(spool)
                assert old_envs[n][constants.ENV_PREEMPT_FILE]

            pending = []
            for i in range(6):
                path = spool / "pending" / f"r{i}.json"
                _atomic_write(str(path), {"id": f"r{i}", "prompt": "hi"})
                pending.append(path)

            victim = _node_of(fake, "default", "sj-serving-0")
            fake.state.inject_maintenance(victim)

            def notices():
                out = {}
                for n in names:
                    path = old_envs[n][constants.ENV_PREEMPT_FILE]
                    if not os.path.exists(path):
                        return None
                    with open(path, encoding="utf-8") as f:
                        out[n] = json.load(f)
                return out
            got = wait_for(notices, msg="serving notices relayed")
            barrier = got["sj-serving-0"]["barrier"]
            # Replica ack = "claimed requests re-spooled, safe to evict".
            for n in names:
                _atomic_write(old_envs[n][constants.ENV_CKPT_FILE],
                              {"step": 0, "barrier": barrier})

            def rebound():
                for n in names:
                    node = _node_of(fake, "default", n)
                    if (not node or node == victim
                            or _pod_uid(fake, "default", n) == old_uids[n]):
                        return False
                return True
            wait_for(rebound, timeout=30, msg="serving gang rebound")

            assert all(p.exists() for p in pending), \
                "pending requests dropped across the drain"
            reasons = {e.reason for e in op.controller.recorder.events}
            assert REASON_CKPT_BARRIER_SAVED in reasons
        finally:
            for a in agents:
                a.stop()
            op.stop()


# ---------------------------------------------------------------------------
# CLI: the lifted flags are accepted on --backend kube
# ---------------------------------------------------------------------------


KUBECONFIG = """\
apiVersion: v1
kind: Config
current-context: test
contexts:
  - name: test
    context:
      cluster: test
      user: test
clusters:
  - name: test
    cluster:
      server: {server}
users:
  - name: test
    user: {{}}
"""


class TestLiftedFlagsOnKube:
    def test_server_constructs_with_all_lifted_flags(self, fake, tmp_path):
        from tf_operator_tpu.cli import Server, build_parser

        kubeconfig = tmp_path / "kubeconfig"
        kubeconfig.write_text(KUBECONFIG.format(server=fake.url),
                              encoding="utf-8")
        qcfg = tmp_path / "queues.yaml"
        qcfg.write_text(QUEUE_YAML, encoding="utf-8")
        args = build_parser().parse_args([
            "--monitoring-port", "0", "--no-leader-elect",
            "--backend", "kube", "--kubeconfig", str(kubeconfig),
            "--enable-gang-scheduling",
            "--enable-tenant-queues", "--queue-config", str(qcfg),
            "--enable-ckpt-coordination",
            "--enable-serving",
            "--agent-relay-dir", str(tmp_path / "relay")])
        server = Server(args)
        try:
            assert server.operator.quota is not None
            assert server.operator.ckpt is not None
            assert server.operator.serving is not None
        finally:
            server.shutdown()
