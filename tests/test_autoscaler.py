"""Serving autoscaler: queue depth + TTFT burn -> numSlices through the
real elastic resize pass (controller/autoscaler.py; docs/serving.md).

Pins the policy (setpoint, band clamp, TTFT-burn grow), the hysteresis
contract (scale-up immediate, scale-down only after continuous
under-demand for the cooldown — a square wave produces at most one
resize per direction per period), every hold reason, and the decision
journal arc served at /debug/jobs/<ns>/<name>."""

import json
import urllib.request

import pytest

from tf_operator_tpu import testutil
from tf_operator_tpu.api import set_defaults
from tf_operator_tpu.api.types import (
    ServingPolicy,
    SliceGroup,
    SliceGroupSpec,
    SliceGroupStatus,
    TPUSliceSpec,
)
from tf_operator_tpu.controller.autoscaler import (
    HOLD_BOUNDS,
    HOLD_COOLDOWN,
    HOLD_SETTLING,
    SIGNAL_QUEUE_DEPTH,
    SIGNAL_TTFT_P99,
    ServingAutoscaler,
    spool_pending_depth,
)
from tf_operator_tpu.controller.gang import (
    PHASE_RUNNING,
    SliceGangScheduler,
)
from tf_operator_tpu.runtime import metrics
from tf_operator_tpu.runtime import store as store_mod
from tf_operator_tpu.runtime import trace as trace_mod
from tf_operator_tpu.runtime.store import Store

NS = "default"


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def make_serving_job(store, name, num_slices=1, min_slices=1,
                     max_slices=3, target=4, cooldown=60.0, slo=None,
                     spool=None):
    job = testutil.new_tpujob(worker=num_slices, name=name, namespace=NS)
    job.spec.slice = TPUSliceSpec(accelerator="v5e-4",
                                  num_slices=num_slices,
                                  min_slices=min_slices,
                                  max_slices=max_slices)
    job.spec.run_policy.serving_policy = ServingPolicy(
        enabled=True, spool_directory=spool or f"/tmp/spool-{name}",
        target_queue_depth_per_slice=target,
        scale_down_cooldown_seconds=cooldown,
        ttft_p99_slo_seconds=slo)
    set_defaults(job)
    store.create(store_mod.TPUJOBS, job)
    return job


def make_group(store, name, num_slices=1, min_slices=1, max_slices=3):
    import datetime as dt

    from tf_operator_tpu.api import constants

    group = SliceGroup(
        spec=SliceGroupSpec(
            min_member=num_slices,
            slice=TPUSliceSpec(accelerator="v5e-4",
                               num_slices=num_slices,
                               min_slices=min_slices,
                               max_slices=max_slices)),
        status=SliceGroupStatus(
            phase=PHASE_RUNNING,
            pending_since=dt.datetime.now(dt.timezone.utc)))
    group.metadata.name = name
    group.metadata.namespace = NS
    group.metadata.labels = {constants.LABEL_JOB_NAME: name}
    store.create(store_mod.SLICEGROUPS, group)
    return group


def harness(name, signals, clock=None, **job_kw):
    """Store + elastic gang + autoscaler around one serving job; the
    autoscaler is ALSO the gang's resize-signal provider, mirroring the
    operator wiring."""
    store = Store()
    make_serving_job(store, name, **job_kw)
    make_group(store, name,
               num_slices=job_kw.get("num_slices", 1),
               min_slices=job_kw.get("min_slices", 1),
               max_slices=job_kw.get("max_slices", 3))
    autoscaler = ServingAutoscaler(store, None, namespace=NS,
                                   signals=signals,
                                   clock=clock or FakeClock())
    gang = SliceGangScheduler(store, elastic=True,
                              resize_signals=autoscaler.signals)
    autoscaler.gang = gang
    return store, gang, autoscaler


def slices_of(store, name):
    return store.get(store_mod.TPUJOBS, NS, name).spec.slice.num_slices


def settle(store, name):
    """Clear the resizing marker like the engine finishing the world
    restart."""
    def clear(group):
        group.status.resizing_reason = ""

    from tf_operator_tpu.runtime import retry as retry_mod

    retry_mod.update_with_conflict_retry(
        store, store_mod.SLICEGROUPS, NS, name, clear, status=True,
        component="test")


def journal_kinds(name):
    recs = trace_mod.JOURNAL.decisions(NS, name) or []
    return [(r["kind"], r["reason"]) for r in recs]


class TestPolicy:
    def test_no_setpoint_means_ignored(self):
        store, gang, asc = harness("as-ignored", lambda ns, n: {
            SIGNAL_QUEUE_DEPTH: 100.0}, target=None)
        asc.evaluate_once()
        assert slices_of(store, "as-ignored") == 1
        assert trace_mod.JOURNAL.decisions(NS, "as-ignored") is None

    def test_grow_on_queue_depth_rides_resize_pass(self):
        grow0 = metrics.gang_resizes.value(direction="grow",
                                           reason="autoscale")
        store, gang, asc = harness("as-grow", lambda ns, n: {
            SIGNAL_QUEUE_DEPTH: 12.0})  # ceil(12/4) = 3
        asc.evaluate_once()
        assert slices_of(store, "as-grow") == 3
        assert metrics.gang_resizes.value(
            direction="grow", reason="autoscale") == grow0 + 1
        assert metrics.autoscaler_target_slices.value(
            job_namespace=NS, job="as-grow") == 3
        kinds = journal_kinds("as-grow")
        assert ("autoscale.up", "queue-depth") in kinds
        assert ("resized", "autoscale") in kinds

    def test_resize_record_carries_the_signals_it_saw(self):
        store, gang, asc = harness("as-signals", lambda ns, n: {
            SIGNAL_QUEUE_DEPTH: 12.0})
        asc.evaluate_once()
        recs = trace_mod.JOURNAL.decisions(NS, "as-signals")
        resized = [r for r in recs if r["kind"] == "resized"]
        assert "serving_queue_depth=12" in resized[0]["message"]

    def test_bounds_hold_when_clamped(self):
        holds0 = metrics.autoscaler_holds.value(reason=HOLD_BOUNDS)
        store, gang, asc = harness("as-bounds", lambda ns, n: {
            SIGNAL_QUEUE_DEPTH: 999.0}, num_slices=3)  # already at max
        asc.evaluate_once()
        assert slices_of(store, "as-bounds") == 3
        assert metrics.autoscaler_holds.value(
            reason=HOLD_BOUNDS) == holds0 + 1
        assert ("autoscale.hold", HOLD_BOUNDS) in journal_kinds(
            "as-bounds")

    def test_ttft_burn_forces_one_slice(self):
        """p99 over the SLO with no backlog growth: latency can burn
        while depth looks fine (slots saturated by long generations)."""
        store, gang, asc = harness("as-ttft", lambda ns, n: {
            SIGNAL_QUEUE_DEPTH: 0.0, SIGNAL_TTFT_P99: 2.0},
            num_slices=2, slo=0.5)
        asc.evaluate_once()
        assert slices_of(store, "as-ttft") == 3
        assert ("autoscale.up", "ttft-slo") in journal_kinds("as-ttft")

    def test_settling_hold_while_resize_in_flight(self):
        holds0 = metrics.autoscaler_holds.value(reason=HOLD_SETTLING)
        store, gang, asc = harness("as-settling", lambda ns, n: {
            SIGNAL_QUEUE_DEPTH: 12.0})
        asc.evaluate_once()  # grow lands, resizing_reason set
        assert slices_of(store, "as-settling") == 3

        def more(ns, n):
            return {SIGNAL_QUEUE_DEPTH: 0.0}

        asc._signals = more  # demand collapses while still settling
        asc.evaluate_once()
        assert slices_of(store, "as-settling") == 3  # held
        assert metrics.autoscaler_holds.value(
            reason=HOLD_SETTLING) == holds0 + 1


class TestHysteresis:
    def test_shrink_waits_out_the_cooldown(self):
        clock = FakeClock()
        shrink0 = metrics.gang_resizes.value(direction="shrink",
                                             reason="autoscale")
        store, gang, asc = harness("as-cool", lambda ns, n: {
            SIGNAL_QUEUE_DEPTH: 0.0}, clock=clock, num_slices=3,
            cooldown=10.0)
        asc.evaluate_once()  # opens the window, holds
        assert slices_of(store, "as-cool") == 3
        assert ("autoscale.hold", HOLD_COOLDOWN) in journal_kinds(
            "as-cool")
        clock.advance(9.0)
        asc.evaluate_once()  # still inside the window
        assert slices_of(store, "as-cool") == 3
        clock.advance(2.0)
        asc.evaluate_once()  # window elapsed: shrink lands
        assert slices_of(store, "as-cool") == 1
        assert metrics.gang_resizes.value(
            direction="shrink", reason="autoscale") == shrink0 + 1
        assert ("autoscale.down", "queue-depth") in journal_kinds(
            "as-cool")

    def test_demand_return_resets_the_window(self):
        """Under-demand must be CONTINUOUS: a burst inside the window
        restarts it, so a flapping trace never shrinks."""
        clock = FakeClock()
        sig = {SIGNAL_QUEUE_DEPTH: 0.0}
        store, gang, asc = harness("as-flap", lambda ns, n: dict(sig),
                                   clock=clock, num_slices=3,
                                   cooldown=10.0)
        asc.evaluate_once()  # window opens
        clock.advance(8.0)
        sig[SIGNAL_QUEUE_DEPTH] = 12.0  # demand covers 3 slices again
        asc.evaluate_once()  # window must reset
        sig[SIGNAL_QUEUE_DEPTH] = 0.0
        clock.advance(8.0)
        asc.evaluate_once()  # NEW window opens here — 16s since the
        assert slices_of(store, "as-flap") == 3  # first one, still held
        clock.advance(8.0)
        asc.evaluate_once()  # 8s of the new window: still held
        assert slices_of(store, "as-flap") == 3
        clock.advance(3.0)
        asc.evaluate_once()  # 11s: continuous under-demand at last
        assert slices_of(store, "as-flap") == 1

    def test_square_wave_one_resize_per_direction_per_period(self):
        """The acceptance shape (docs/serving.md): a square-wave load
        makes at most ONE resize per direction per period — up on the
        rising edge, down one cooldown into the trough — and the whole
        arc is reconstructable from the decision journal."""
        clock = FakeClock()
        sig = {SIGNAL_QUEUE_DEPTH: 0.0}
        grow0 = metrics.gang_resizes.value(direction="grow",
                                           reason="autoscale")
        shrink0 = metrics.gang_resizes.value(direction="shrink",
                                             reason="autoscale")
        store, gang, asc = harness("as-wave", lambda ns, n: dict(sig),
                                   clock=clock, cooldown=2.0)
        periods, period, step = 3, 10.0, 0.5
        t = 0.0
        while t < periods * period:
            sig[SIGNAL_QUEUE_DEPTH] = (
                12.0 if (t % period) < period / 2 else 0.0)
            asc.evaluate_once()
            settle(store, "as-wave")  # engine finishes each restart
            clock.advance(step)
            t += step
        grows = metrics.gang_resizes.value(
            direction="grow", reason="autoscale") - grow0
        shrinks = metrics.gang_resizes.value(
            direction="shrink", reason="autoscale") - shrink0
        assert grows == periods  # exactly one per rising edge
        assert shrinks == periods  # exactly one per trough
        # Journal reconstruction: alternating up/down arc, no other
        # applied decisions.
        decisions = [r for r in trace_mod.JOURNAL.decisions(NS, "as-wave")
                     if r["kind"] in ("autoscale.up", "autoscale.down")]
        arc = [r["kind"] for r in decisions]
        assert arc == ["autoscale.up", "autoscale.down"] * periods
        for r in decisions:
            assert "queue_depth=" in r["message"]  # inputs preserved

    def test_journal_served_at_debug_endpoint(self):
        """The operator-facing contract: the autoscale arc is readable
        from /debug/jobs/<ns>/<name> — no log archaeology."""
        from tf_operator_tpu.runtime.monitoring import MonitoringServer

        store, gang, asc = harness("as-debug", lambda ns, n: {
            SIGNAL_QUEUE_DEPTH: 12.0})
        asc.evaluate_once()
        server = MonitoringServer(port=0)
        server.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}"
                    f"/debug/jobs/{NS}/as-debug") as resp:
                payload = json.loads(resp.read())
        finally:
            server.stop()
        kinds = [r["kind"] for r in payload["decisions"]]
        assert "autoscale.up" in kinds


class TestSignals:
    def test_spool_pending_depth(self, tmp_path):
        pending = tmp_path / "pending"
        pending.mkdir()
        for i in range(3):
            (pending / f"r{i}.json").write_text("{}")
        (pending / "ignored.tmp").write_text("")
        assert spool_pending_depth(str(tmp_path)) == 3.0
        assert spool_pending_depth(str(tmp_path / "missing")) == 0.0

    def test_default_provider_reads_job_spool(self, tmp_path):
        (tmp_path / "pending").mkdir()
        (tmp_path / "pending" / "a.json").write_text("{}")
        store = Store()
        make_serving_job(store, "as-sig", spool=str(tmp_path))
        asc = ServingAutoscaler(store, None, namespace=NS)
        sig = asc.signals(NS, "as-sig")
        assert sig[SIGNAL_QUEUE_DEPTH] == 1.0

    def test_injected_provider_failure_is_safe(self):
        def boom(ns, n):
            raise RuntimeError("scrape failed")

        store, gang, asc = harness("as-boom", boom)
        asc.evaluate_once()  # depth defaults to 0 -> no resize
        assert slices_of(store, "as-boom") == 1


pytestmark = pytest.mark.control_plane
