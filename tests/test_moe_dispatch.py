"""Einsum-vs-gather MoE dispatch equivalence (round-6 tentpole).

``MixtralConfig.dispatch="gather"`` replaces the GShard one-hot
dispatch/combine einsums with sort/gather token routing. The contract
is NUMERICS EQUIVALENCE: identical capacity dropping (the stable sort
preserves the einsum path's token-major priority order), identical
outputs, grads, aux loss, and dropped-assignment counts — so the two
paths are freely interchangeable (same params, same checkpoints) and
the bench A/B (`bench_moe.py --dispatch`) compares implementations,
never models. Everything here is CPU-sized, fixed seed.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tf_operator_tpu.models.mixtral import (
    Mixtral,
    MoELayer,
    make_moe_lm_loss,
    mixtral_tiny,
    param_logical_axes as moe_axes,
)
from tf_operator_tpu.parallel.mesh import MeshConfig, make_mesh, use_mesh
from tf_operator_tpu.parallel.sharding import MOE_RULES
from tf_operator_tpu.train.trainer import Trainer


def f32_cfg(**kw):
    """Tiny Mixtral in f32 (bf16 would hide real mismatches in cast
    noise) with both dispatch variants derivable via replace."""
    return dataclasses.replace(mixtral_tiny(), dtype=jnp.float32, **kw)


def tokens(seed, batch, seq, vocab):
    return jnp.asarray(np.random.default_rng(seed).integers(
        0, vocab, (batch, seq)), jnp.int32)


def moe_layer_pair(cfg):
    """(einsum layer, gather layer) sharing one param init — param
    names/shapes are dispatch-independent by construction."""
    le = MoELayer(dataclasses.replace(cfg, dispatch="einsum"))
    lg = MoELayer(dataclasses.replace(cfg, dispatch="gather"))
    return le, lg


def test_forward_logits_and_aux_match():
    cfg = f32_cfg()
    tok = tokens(1, 4, 32, cfg.vocab_size)
    model_e = Mixtral(dataclasses.replace(cfg, dispatch="einsum"))
    model_g = Mixtral(dataclasses.replace(cfg, dispatch="gather"))
    params = model_e.init(jax.random.PRNGKey(0), tok)
    logits_e, aux_e = jax.jit(model_e.apply)(params, tok)
    logits_g, aux_g = jax.jit(model_g.apply)(params, tok)
    np.testing.assert_allclose(np.asarray(logits_e), np.asarray(logits_g),
                               atol=1e-5, rtol=1e-5)
    assert abs(float(aux_e) - float(aux_g)) < 1e-6


def test_grads_match():
    cfg = f32_cfg()
    tok = tokens(2, 4, 32, cfg.vocab_size)
    model_e = Mixtral(dataclasses.replace(cfg, dispatch="einsum"))
    model_g = Mixtral(dataclasses.replace(cfg, dispatch="gather"))
    params = model_e.init(jax.random.PRNGKey(0), tok)

    def loss(model):
        def f(p):
            logits, aux = model.apply(p, tok)
            return (jnp.mean(logits.astype(jnp.float32) ** 2)
                    + cfg.aux_loss_weight * aux)
        return f

    g_e = jax.jit(jax.grad(loss(model_e)))(params)
    g_g = jax.jit(jax.grad(loss(model_g)))(params)
    flat_e = jax.tree_util.tree_leaves_with_path(g_e)
    flat_g = jax.tree.leaves(g_g)
    assert len(flat_e) == len(flat_g)
    for (path, a), b in zip(flat_e, flat_g):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-6, rtol=1e-4,
            err_msg=f"grad mismatch at {jax.tree_util.keystr(path)}")


def test_over_capacity_drops_identically():
    """capacity_factor 0.25 forces heavy dropping: both paths must drop
    the SAME assignments (count pinned via the sown intermediate) and
    still produce identical outputs and aux."""
    cfg = f32_cfg(capacity_factor=0.25)
    layer_e, layer_g = moe_layer_pair(cfg)
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 32, cfg.hidden),
                          jnp.float32)
    params = layer_e.init(jax.random.PRNGKey(4), x)
    (y_e, aux_e), inter_e = layer_e.apply(params, x,
                                          mutable=["intermediates"])
    (y_g, aux_g), inter_g = layer_g.apply(params, x,
                                          mutable=["intermediates"])
    dropped_e = int(inter_e["intermediates"]["dropped_assignments"][0])
    dropped_g = int(inter_g["intermediates"]["dropped_assignments"][0])
    assert dropped_e == dropped_g
    assert dropped_e > 0, "over-capacity case must actually drop"
    np.testing.assert_allclose(np.asarray(y_e), np.asarray(y_g),
                               atol=1e-6, rtol=1e-5)
    assert abs(float(aux_e) - float(aux_g)) < 1e-6


def test_no_drops_when_capacity_ample():
    """Sanity on the drop accounting itself: capacity >= T*K/E never
    drops, under either implementation."""
    # capacity_factor = E makes capacity = T*K — room for everything.
    cfg = f32_cfg(capacity_factor=float(mixtral_tiny().n_experts))
    layer_e, layer_g = moe_layer_pair(cfg)
    x = jax.random.normal(jax.random.PRNGKey(5), (2, 16, cfg.hidden),
                          jnp.float32)
    params = layer_e.init(jax.random.PRNGKey(6), x)
    for layer in (layer_e, layer_g):
        _, inter = layer.apply(params, x, mutable=["intermediates"])
        assert int(inter["intermediates"]["dropped_assignments"][0]) == 0


def test_unknown_dispatch_rejected():
    cfg = f32_cfg(dispatch="scatter_gather_v2")
    x = jnp.zeros((1, 8, cfg.hidden), jnp.float32)
    with pytest.raises(ValueError, match="dispatch"):
        MoELayer(cfg).init(jax.random.PRNGKey(0), x)


def test_gather_trains_under_expert_parallelism():
    """ep=2 sharded smoke: the gather path compiles and trains on a
    dp×ep mesh with experts sharded over ep, and its loss trajectory
    matches the einsum path step-for-step (same params, same batch)."""
    losses = {}
    for dispatch in ("einsum", "gather"):
        mesh = make_mesh(MeshConfig(dp=4, ep=2))
        cfg = dataclasses.replace(mixtral_tiny(), dispatch=dispatch)
        tr = Trainer(model=Mixtral(cfg), param_axes_fn=moe_axes,
                     rules=MOE_RULES, mesh=mesh,
                     optimizer=optax.adam(1e-2),
                     loss_fn=make_moe_lm_loss(cfg.aux_loss_weight),
                     model_inputs_fn=lambda b: (b["inputs"][:, :-1],))
        rng = jax.random.PRNGKey(0)
        sample = {"inputs": jnp.zeros((8, 33), jnp.int32)}
        with use_mesh(mesh):
            state, sh = tr.init(rng, sample)
            spec = state.params["blocks"]["moe"]["w_gate"].sharding.spec
            assert "ep" in jax.tree.leaves(tuple(spec))
            step = tr.make_train_step(sh, sample)
            tok = {"inputs": jnp.asarray(np.random.default_rng(0).integers(
                0, cfg.vocab_size, (8, 33)), jnp.int32)}
            run = []
            for _ in range(4):
                state, m = step(state, tok)
                run.append(float(m["loss"]))
        losses[dispatch] = run
    assert losses["gather"][-1] < losses["gather"][0] - 0.5
    np.testing.assert_allclose(losses["einsum"], losses["gather"],
                               rtol=5e-3)


# CI shard (pyproject [tool.pytest.ini_options] markers)
pytestmark = pytest.mark.compute
