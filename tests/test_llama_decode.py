"""Llama incremental decode: prefill + N x decode_step must reproduce
the full-sequence forward exactly (f32, <= 1e-5), including per-slot
cache insertion at staggered positions and a tp=2 sharded smoke with
the KV cache constrained to the mesh."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tf_operator_tpu.models.llama import (
    Llama,
    decode_step,
    init_cache,
    insert_cache,
    llama_tiny,
    prefill,
)
from tf_operator_tpu.parallel.mesh import MeshConfig, make_mesh, use_mesh

ATOL = 1e-5


@pytest.fixture(scope="module")
def setup():
    cfg = dataclasses.replace(llama_tiny(vocab_size=64, max_seq_len=32),
                              dtype=jnp.float32)
    model = Llama(cfg)
    rng = jax.random.PRNGKey(0)
    tokens = jax.random.randint(rng, (2, 12), 0, cfg.vocab_size)
    params = model.init(rng, tokens)["params"]
    decode_model = Llama(dataclasses.replace(cfg, decode=True))
    full = model.apply({"params": params}, tokens)
    return cfg, model, decode_model, params, tokens, full


def test_decode_model_shares_param_tree(setup):
    cfg, model, decode_model, params, tokens, _ = setup
    # Trained checkpoints load unchanged into the decode model: the
    # param trees are structurally identical.
    decode_params = decode_model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 1), jnp.int32),
        positions=jnp.zeros((1, 1), jnp.int32))["params"]
    assert (jax.tree_util.tree_structure(params)
            == jax.tree_util.tree_structure(decode_params))


def test_prefill_matches_full_forward(setup):
    cfg, _, decode_model, params, tokens, full = setup
    b, s = tokens.shape
    cache = init_cache(decode_model, params, b)
    positions = jnp.broadcast_to(jnp.arange(s), (b, s))
    logits, cache = prefill(decode_model, params, cache, tokens, positions)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(full),
                               atol=ATOL)


def test_prefill_plus_n_decode_steps_match(setup):
    cfg, _, decode_model, params, tokens, full = setup
    b, s = tokens.shape
    split = 5
    cache = init_cache(decode_model, params, b)
    positions = jnp.broadcast_to(jnp.arange(split), (b, split))
    logits, cache = prefill(decode_model, params, cache,
                            tokens[:, :split], positions)
    np.testing.assert_allclose(np.asarray(logits),
                               np.asarray(full[:, :split]), atol=ATOL)
    for t in range(split, s):
        logits, cache = decode_step(
            decode_model, params, cache, tokens[:, t:t + 1],
            jnp.full((b, 1), t, jnp.int32))
        np.testing.assert_allclose(np.asarray(logits[:, 0]),
                                   np.asarray(full[:, t]), atol=ATOL)


def test_insert_cache_staggered_slots(setup):
    """Continuous-batching shape: two sequences prefilled SEPARATELY
    (per-request prefill), inserted into different slots, then decoded
    in one batched call at DIFFERENT positions — each must match its
    own full-sequence forward."""
    cfg, model, decode_model, params, tokens, full = setup
    lens = (4, 9)
    cache = init_cache(decode_model, params, 2)
    stage = init_cache(decode_model, params, 1)
    for slot, ln in enumerate(lens):
        pos = jnp.arange(ln, dtype=jnp.int32)[None, :]
        _, stage = prefill(decode_model, params, stage,
                           tokens[slot:slot + 1, :ln], pos)
        cache = insert_cache(cache, stage, slot)
    # One batched decode step: row i feeds token at its own position.
    step_tokens = jnp.stack([tokens[0, lens[0]], tokens[1, lens[1]]])[:, None]
    step_pos = jnp.asarray(lens, jnp.int32)[:, None]
    logits, cache = decode_step(decode_model, params, cache,
                                step_tokens, step_pos)
    for slot, ln in enumerate(lens):
        np.testing.assert_allclose(np.asarray(logits[slot, 0]),
                                   np.asarray(full[slot, ln]), atol=ATOL)


def test_padded_prefill_tail_is_harmless(setup):
    """Prefill padded past the real prompt (the runner's power-of-two
    buckets): the garbage KV rows past the prompt must be overwritten
    before any later step attends them."""
    cfg, _, decode_model, params, tokens, full = setup
    b, s = tokens.shape
    ln, pad = 6, 10
    cache = init_cache(decode_model, params, b)
    padded = jnp.zeros((b, pad), jnp.int32).at[:, :ln].set(tokens[:, :ln])
    positions = jnp.broadcast_to(jnp.arange(pad), (b, pad))
    logits, cache = prefill(decode_model, params, cache, padded, positions)
    np.testing.assert_allclose(np.asarray(logits[:, :ln]),
                               np.asarray(full[:, :ln]), atol=ATOL)
    # Continue decoding THROUGH the padded region: positions ln..pad are
    # rewritten by their own decode steps before being attended.
    for t in range(ln, s):
        logits, cache = decode_step(
            decode_model, params, cache, tokens[:, t:t + 1],
            jnp.full((b, 1), t, jnp.int32))
        np.testing.assert_allclose(np.asarray(logits[:, 0]),
                                   np.asarray(full[:, t]), atol=ATOL)


def test_tp2_sharded_decode_smoke(setup):
    """tp=2 mesh: the KV cache's kv_heads axis shards over tp
    (parallel/sharding.py LLAMA_RULES via sharding.constrain); jitted
    prefill/decode under the mesh must still match the unsharded
    reference."""
    cfg, _, decode_model, params, tokens, full = setup
    devices = jax.devices()
    if len(devices) < 2:
        pytest.skip("needs >= 2 devices (conftest forces 8)")
    mesh = make_mesh(MeshConfig(tp=2), devices=devices[:2])
    b, s = tokens.shape
    split = 5
    with use_mesh(mesh):
        pf = jax.jit(lambda p, c, t, pos: prefill(decode_model, p, c,
                                                  t, pos))
        dc = jax.jit(lambda p, c, t, pos: decode_step(decode_model, p, c,
                                                      t, pos))
        cache = init_cache(decode_model, params, b)
        positions = jnp.broadcast_to(jnp.arange(split), (b, split))
        logits, cache = pf(params, cache, tokens[:, :split], positions)
        np.testing.assert_allclose(np.asarray(logits),
                                   np.asarray(full[:, :split]), atol=ATOL)
        for t in range(split, s):
            logits, cache = dc(params, cache, tokens[:, t:t + 1],
                               jnp.full((b, 1), t, jnp.int32))
            np.testing.assert_allclose(np.asarray(logits[:, 0]),
                                       np.asarray(full[:, t]), atol=ATOL)


def test_decode_requires_positions(setup):
    cfg, _, decode_model, params, tokens, _ = setup
    cache = init_cache(decode_model, params, 2)
    with pytest.raises(ValueError, match="positions"):
        decode_model.apply({"params": params, "cache": cache}, tokens,
                           mutable=["cache"])


def test_training_forward_unchanged_by_decode_field(setup):
    """The decode field must not perturb the training path: same params,
    same tokens, same logits with decode=False (the existing model
    suites pin the broader behavior; this pins the config plumbing)."""
    cfg, model, _, params, tokens, full = setup
    again = model.apply({"params": params}, tokens)
    np.testing.assert_array_equal(np.asarray(again), np.asarray(full))


# CI shard (pyproject [tool.pytest.ini_options] markers)
pytestmark = pytest.mark.compute
