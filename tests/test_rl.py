"""Heterogeneous gangs: RolePolicy resolution and the RL actor–learner
workload (docs/rl.md).

Pins the whole role-policy surface end to end:

- resolver defaults reproduce the legacy hardcoded role sets exactly
  (flag-off parity: a job without a rolePolicy block is byte-identical
  to one from before the field existed, bootstrap hash included);
- chip stamping derives from chipConsuming, not role names — a
  CPU-only actor pool never gets google.com/tpu resources or the
  nodepool toleration, and an override flips either direction;
- actors get the learner-endpoint env OUTSIDE every bootstrap hash, so
  actor-pool resizes (gang.resize_role) and learner resizes never
  restart the other side;
- gang admission counts an elastic-band role at its minReplicas floor;
- save-before-evict barriers skip roles that EXPLICITLY opted out
  (disruptionClass evict/ignore) and heterogeneous jobs publish the
  learner goodput lane;
- slice-health episodes touching only evict/ignore-class pods take the
  per-pod actor lane (no barrier, no gang drain); a learner on the
  same bad node sends the gang down the unchanged atomic-drain path;
- e2e: an actor kill storm (>= 50% of the pool) mid-train leaves every
  learner pod's uid, bootstrap-hash annotation, and the job's
  committed step untouched while the pool heals.
"""

import datetime as dt
import json
import time

import pytest

from tf_operator_tpu import testutil
from tf_operator_tpu.api import constants, set_defaults
from tf_operator_tpu.api.types import (
    CheckpointPolicy,
    CheckpointRecord,
    CheckpointRecordStatus,
    DisruptionClass,
    HealthPolicy,
    Node,
    NodeSpec,
    NodeStatus,
    PodPhase,
    ReplicaType,
    RolePolicy,
    SliceGroup,
    SliceGroupSpec,
    SliceGroupStatus,
    TPUSliceSpec,
    effective_role_policy,
    elastic_role_types,
)
from tf_operator_tpu.api.validation import ValidationError, validate_job
from tf_operator_tpu.bootstrap import learner_endpoints
from tf_operator_tpu.controller.ckpt import CheckpointCoordinator
from tf_operator_tpu.controller.engine import EngineConfig
from tf_operator_tpu.controller.gang import (
    PHASE_RUNNING,
    SliceGangScheduler,
)
from tf_operator_tpu.controller.health import SliceHealthController
from tf_operator_tpu.controller.tpu_controller import TPUJobController
from tf_operator_tpu.runtime import metrics
from tf_operator_tpu.runtime import store as store_mod
from tf_operator_tpu.runtime.events import (
    REASON_ACTOR_EVICTED,
    REASON_SLICE_DRAINED,
    Recorder,
)
from tf_operator_tpu.runtime.store import Store

NS = "default"


def _now():
    return dt.datetime.now(dt.timezone.utc)


def actor_policy(min_replicas=1, max_replicas=4,
                 disruption=DisruptionClass.EVICT):
    return RolePolicy(chip_consuming=False, preemptible=True,
                      min_replicas=min_replicas,
                      max_replicas=max_replicas,
                      disruption_class=disruption)


def make_rl_job(worker=2, actor=4, name="rl", namespace=NS,
                accelerator="v5e-4", policy=None, ckpt=False):
    job = testutil.new_tpujob(worker=worker, actor=actor, name=name,
                              namespace=namespace,
                              accelerator=accelerator)
    job.spec.replica_specs[ReplicaType.ACTOR].role_policy = (
        policy if policy is not None
        else actor_policy(max_replicas=actor))
    if ckpt:
        job.spec.run_policy.checkpoint_policy = CheckpointPolicy(
            enabled=True, directory="/tmp/ckpt",
            barrier_timeout_seconds=30.0)
    set_defaults(job)
    return job


def make_group(store, name, namespace=NS, min_member=2):
    group = SliceGroup(
        spec=SliceGroupSpec(min_member=min_member,
                            slice=TPUSliceSpec(accelerator="v5e-4")),
        status=SliceGroupStatus(phase=PHASE_RUNNING,
                                pending_since=_now()))
    group.metadata.name = name
    group.metadata.namespace = namespace
    group.metadata.labels = {constants.LABEL_JOB_NAME: name}
    store.create(store_mod.SLICEGROUPS, group)
    return group


def add_pod(store, job, rtype, index, node="", phase=PodPhase.RUNNING):
    pod = testutil.new_pod(job, rtype, index, phase=phase)
    pod.spec.node_name = node
    pod.metadata.annotations[constants.ANNOTATION_GANG_GROUP] = \
        job.metadata.name
    store.create(store_mod.PODS, pod)
    return pod


def wait_for(predicate, timeout=20.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {msg}")


# --- resolver -------------------------------------------------------------

def test_resolver_defaults_match_legacy_role_sets():
    """No rolePolicy anywhere: every role resolves to its historical
    hardcoded treatment (the flag-off parity contract)."""
    job = testutil.new_tpujob(worker=2, ps=1, chief=1, evaluator=1,
                              actor=2)
    job.spec.replica_specs[ReplicaType.ACTOR].role_policy = None
    for rt, chip, disruption, data_plane in (
            (ReplicaType.WORKER, True, DisruptionClass.BARRIER, True),
            (ReplicaType.CHIEF, False, DisruptionClass.EVICT, True),
            (ReplicaType.PS, False, DisruptionClass.EVICT, False),
            (ReplicaType.EVALUATOR, False, DisruptionClass.EVICT, False),
            (ReplicaType.ACTOR, False, DisruptionClass.EVICT, False),
            # Serving's former special cases are now resolver defaults.
            (ReplicaType.SERVING, True, DisruptionClass.BARRIER, False)):
        eff = effective_role_policy(job, rt)
        assert eff.chip_consuming is chip, rt
        assert eff.disruption_class == disruption, rt
        assert eff.data_plane is data_plane, rt
        assert eff.explicit is False and eff.explicit_disruption is False
        assert eff.elastic is False and eff.preemptible is False
    assert elastic_role_types(job) == []


def test_resolver_override_and_elastic_band():
    job = make_rl_job()
    eff = effective_role_policy(job, ReplicaType.ACTOR)
    assert eff.explicit and eff.explicit_disruption
    assert eff.chip_consuming is False and eff.preemptible is True
    assert (eff.min_replicas, eff.max_replicas) == (1, 4)
    assert eff.disruption_class == DisruptionClass.EVICT
    assert eff.elastic is True and eff.barrier is False
    assert elastic_role_types(job) == [ReplicaType.ACTOR]
    # A band needs BOTH bounds to opt into the resize lane.
    job.spec.replica_specs[ReplicaType.ACTOR].role_policy = RolePolicy(
        chip_consuming=False, min_replicas=1)
    assert effective_role_policy(job, ReplicaType.ACTOR).elastic is False


def test_data_plane_membership_is_not_a_policy_knob():
    """dataPlane is a property of what the role runs — a RolePolicy
    cannot move a role in or out of the ranked jax world."""
    job = testutil.new_tpujob(worker=2, actor=2)
    job.spec.replica_specs[ReplicaType.WORKER].role_policy = RolePolicy(
        chip_consuming=False, preemptible=True)
    assert effective_role_policy(job, ReplicaType.WORKER).data_plane
    job.spec.replica_specs[ReplicaType.ACTOR].role_policy = RolePolicy(
        chip_consuming=True)
    assert not effective_role_policy(job, ReplicaType.ACTOR).data_plane


# --- validation -----------------------------------------------------------

def test_role_policy_validation():
    job = make_rl_job()
    validate_job(job)  # the canonical RL shape is valid

    spec = job.spec.replica_specs[ReplicaType.ACTOR]
    spec.role_policy = actor_policy(disruption="sometimes")
    with pytest.raises(ValidationError, match="disruptionClass"):
        validate_job(job)

    spec.role_policy = actor_policy(min_replicas=-1)
    with pytest.raises(ValidationError, match="minReplicas"):
        validate_job(job)

    spec.role_policy = actor_policy(min_replicas=3, max_replicas=2)
    with pytest.raises(ValidationError, match="maxReplicas"):
        validate_job(job)

    spec.role_policy = RolePolicy(chip_consuming=False, max_replicas=4)
    with pytest.raises(ValidationError, match="set together"):
        validate_job(job)

    # replicas must start inside the band.
    spec.role_policy = actor_policy(min_replicas=1, max_replicas=2)
    with pytest.raises(ValidationError, match="maxReplicas"):
        validate_job(job)

    # Chip holders resize in whole slices, never by replica count.
    spec.role_policy = actor_policy()
    worker = job.spec.replica_specs[ReplicaType.WORKER]
    worker.role_policy = RolePolicy(min_replicas=1, max_replicas=4)
    with pytest.raises(ValidationError, match="non-chip-consuming"):
        validate_job(job)


# --- pod shape (chip stamping from chipConsuming, not role names) ---------

def test_actor_pod_is_cpu_only_with_learner_endpoints():
    store = Store()
    controller = TPUJobController(store)
    job = make_rl_job()
    pod = testutil.new_pod(job, ReplicaType.ACTOR, 0)
    controller.set_cluster_spec(job, pod, ReplicaType.ACTOR, 0)
    container = pod.spec.containers[0]
    # CPU-only: no chip request, no TPU-nodepool toleration.
    assert all(constants.RESOURCE_TPU not in c.resources
               for c in pod.spec.containers)
    assert all(t.key != constants.RESOURCE_TPU
               for t in pod.spec.tolerations)
    # Outside the ranked world: no jax.distributed identity env.
    assert not any(k.startswith("JAX_") for k in container.env)
    # ...but full discovery of it: the learner endpoint list.
    eps = container.env[constants.ENV_LEARNER_ENDPOINTS]
    assert eps == learner_endpoints(job)
    assert len(eps.split(",")) == 2
    assert "worker-0" in eps and "worker-1" in eps


def test_worker_pod_keeps_chips_and_world_env():
    store = Store()
    controller = TPUJobController(store)
    job = make_rl_job()
    pod = testutil.new_pod(job, ReplicaType.WORKER, 0)
    controller.set_cluster_spec(job, pod, ReplicaType.WORKER, 0)
    container = pod.spec.containers[0]
    assert constants.RESOURCE_TPU in container.resources
    assert any(t.key == constants.RESOURCE_TPU
               for t in pod.spec.tolerations)
    assert container.env["JAX_PROCESS_ID"] == "0"
    # Learner discovery is the satellite roles' env, not the world's.
    assert constants.ENV_LEARNER_ENDPOINTS not in container.env


def test_chip_stamping_follows_chip_consuming_not_role_name():
    store = Store()
    controller = TPUJobController(store)
    job = make_rl_job()
    # Worker overridden to chipConsuming=False: no chips despite name.
    job.spec.replica_specs[ReplicaType.WORKER].role_policy = RolePolicy(
        chip_consuming=False)
    pod = testutil.new_pod(job, ReplicaType.WORKER, 0)
    controller.set_cluster_spec(job, pod, ReplicaType.WORKER, 0)
    assert all(constants.RESOURCE_TPU not in c.resources
               for c in pod.spec.containers)
    assert all(t.key != constants.RESOURCE_TPU
               for t in pod.spec.tolerations)
    # Actor overridden to chipConsuming=True (no band): chips despite
    # name — e.g. an actor pool doing on-chip inference.
    job2 = make_rl_job(policy=RolePolicy(chip_consuming=True))
    pod2 = testutil.new_pod(job2, ReplicaType.ACTOR, 0)
    controller.set_cluster_spec(job2, pod2, ReplicaType.ACTOR, 0)
    assert constants.RESOURCE_TPU in pod2.spec.containers[0].resources


# --- flag-off parity ------------------------------------------------------

def test_empty_role_policy_block_is_byte_identical_for_worker():
    """An empty rolePolicy {} on a worker resolves to every default:
    same rendered env, same bootstrap hash as no block at all."""
    store = Store()
    controller = TPUJobController(store)
    plain = testutil.new_tpujob(worker=2, name="par", accelerator="v5e-4")
    policied = testutil.new_tpujob(worker=2, name="par",
                                   accelerator="v5e-4")
    policied.metadata.uid = plain.metadata.uid
    policied.spec.replica_specs[ReplicaType.WORKER].role_policy = \
        RolePolicy()

    def shape(job):
        pod = testutil.new_pod(job, ReplicaType.WORKER, 0)
        controller.set_cluster_spec(job, pod, ReplicaType.WORKER, 0)
        return (dict(pod.spec.containers[0].env),
                dict(pod.spec.containers[0].resources),
                controller._compute_bootstrap_hash(
                    job, ReplicaType.WORKER, 0))

    assert shape(plain) == shape(policied)


def test_default_satellite_roles_still_get_barrier_notices():
    """Explicitness gates the notice skip: a ps pod with NO rolePolicy
    resolves to evict-class by default but keeps getting the preempt
    notice it always got (resolver defaults must not relax behavior);
    an EXPLICIT evict-class actor never gets one."""
    store = Store()
    ckpt = CheckpointCoordinator(store)
    job = make_rl_job(ckpt=True)
    job.spec.replica_specs[ReplicaType.PS] = testutil.new_replica_spec(1)
    set_defaults(job)
    store.create(store_mod.TPUJOBS, job)
    add_pod(store, job, ReplicaType.WORKER, 0)
    add_pod(store, job, ReplicaType.PS, 0)
    add_pod(store, job, ReplicaType.ACTOR, 0)

    assert ckpt.ready_to_evict(NS, "rl", "test drain") is False
    notice = constants.ANNOTATION_PREEMPT_NOTICE
    assert notice in store.get(
        store_mod.PODS, NS, "rl-worker-0").metadata.annotations
    assert notice in store.get(
        store_mod.PODS, NS, "rl-ps-0").metadata.annotations
    assert notice not in store.get(
        store_mod.PODS, NS, "rl-actor-0").metadata.annotations


# --- bootstrap-hash invariance --------------------------------------------

def test_actor_pool_resize_changes_no_bootstrap_hash():
    """The elastic band's cluster entry is outside EVERY role's digest:
    growing/shrinking the pool restarts nothing — not the learners,
    not the band's own survivors. And the actor digest drops the
    data-plane lists, so a learner resize leaves actors running too."""
    store = Store()
    controller = TPUJobController(store)
    job = make_rl_job(worker=2, actor=2)

    def hashes(j):
        return {rt: controller._compute_bootstrap_hash(j, rt, 0)
                for rt in (ReplicaType.WORKER, ReplicaType.ACTOR)}

    before = hashes(job)
    job.spec.replica_specs[ReplicaType.ACTOR].replicas = 4
    assert hashes(job) == before

    # Learner (worker) resize: the actor hash must hold (actors dial
    # learners via ENV outside the hash); the worker world restarts.
    job.spec.replica_specs[ReplicaType.WORKER].replicas = 3
    after = hashes(job)
    assert after[ReplicaType.ACTOR] == before[ReplicaType.ACTOR]
    assert after[ReplicaType.WORKER] != before[ReplicaType.WORKER]


# --- gang admission + the resize lane -------------------------------------

def test_gang_min_member_counts_elastic_band_at_floor():
    store = Store()
    gang = SliceGangScheduler(store, total_chips=None)
    job = make_rl_job(worker=2, actor=4,
                      policy=actor_policy(min_replicas=1, max_replicas=6))
    store.create(store_mod.TPUJOBS, job)
    gang.sync_slice_group(job, job.spec.replica_specs)
    group = store.get(store_mod.SLICEGROUPS, NS, "rl")
    assert group.spec.min_member == 2 + 1  # workers + the band's floor

    # Without a band the role counts in full (byte-identical default).
    job2 = make_rl_job(worker=2, actor=4, name="rl2", policy=None)
    job2.spec.replica_specs[ReplicaType.ACTOR].role_policy = None
    store.create(store_mod.TPUJOBS, job2)
    gang.sync_slice_group(job2, job2.spec.replica_specs)
    assert store.get(store_mod.SLICEGROUPS, NS,
                     "rl2").spec.min_member == 2 + 4


def test_resize_role_grow_shrink_clamp_and_prune():
    store = Store()
    ckpt = CheckpointCoordinator(store)
    # elastic=False on purpose: that flag gates SLICE resizes; the
    # replica-count lane works without it (and on both backends).
    gang = SliceGangScheduler(store, total_chips=None, ckpt=ckpt,
                              elastic=False)
    job = make_rl_job(worker=2, actor=2,
                      policy=actor_policy(min_replicas=1, max_replicas=4))
    store.create(store_mod.TPUJOBS, job)

    def replicas():
        return store.get(store_mod.TPUJOBS, NS,
                         "rl").spec.replica_specs["actor"].replicas

    assert gang.resize_role(NS, "rl", "actor", 4, "scale", "demand") \
        is True
    assert replicas() == 4
    assert metrics.actor_pool_replicas.value(
        job_namespace=NS, job="rl", replica_type="actor") == 4

    # Clamped to the band on both ends.
    assert gang.resize_role(NS, "rl", "actor", 99, "scale", "x") is False
    assert replicas() == 4  # already at the clamped target: no-op
    assert gang.resize_role(NS, "rl", "actor", 0, "scale", "x") is True
    assert replicas() == 1
    assert metrics.actor_pool_replicas.value(
        job_namespace=NS, job="rl", replica_type="actor") == 1

    # A shrink prunes departed replicas' CheckpointRecords so they
    # never pin committed_step (actors normally publish none).
    assert gang.resize_role(NS, "rl", "actor", 3, "scale", "x") is True
    for i in range(3):
        rec = CheckpointRecord(status=CheckpointRecordStatus(
            step=5, progress_step=5))
        rec.metadata.name = f"rl-actor-{i}"
        rec.metadata.namespace = NS
        rec.metadata.labels = {constants.LABEL_JOB_NAME: "rl"}
        store.create(store_mod.CHECKPOINTRECORDS, rec)
    assert gang.resize_role(NS, "rl", "actor", 1, "scale", "x") is True
    assert store.try_get(store_mod.CHECKPOINTRECORDS, NS,
                         "rl-actor-0") is not None
    for i in (1, 2):
        assert store.try_get(store_mod.CHECKPOINTRECORDS, NS,
                             f"rl-actor-{i}") is None

    # Not applicable: unknown job, or a role without an explicit band.
    assert gang.resize_role(NS, "nope", "actor", 2, "scale", "x") is None
    assert gang.resize_role(NS, "rl", "worker", 3, "scale", "x") is None


# --- ckpt: barriers skip explicit evict-class roles -----------------------

def test_barrier_skips_actors_and_publishes_learner_goodput():
    store = Store()
    ckpt = CheckpointCoordinator(store)
    job = make_rl_job(worker=2, actor=2, name="rlb", ckpt=True)
    store.create(store_mod.TPUJOBS, job)
    worker_pods = [add_pod(store, job, ReplicaType.WORKER, i)
                   for i in range(2)]
    add_pod(store, job, ReplicaType.ACTOR, 0)
    add_pod(store, job, ReplicaType.ACTOR, 1)

    assert ckpt.ready_to_evict(NS, "rlb", "drain") is False
    notice = json.loads(store.get(
        store_mod.PODS, NS,
        "rlb-worker-0").metadata.annotations[
            constants.ANNOTATION_PREEMPT_NOTICE])
    # Actors are neither stamped nor waited on: a Running actor with no
    # CheckpointRecord can never gate the barrier.
    for i in range(2):
        pod = store.get(store_mod.PODS, NS, f"rlb-actor-{i}")
        assert constants.ANNOTATION_PREEMPT_NOTICE \
            not in pod.metadata.annotations

    # Full LEARNER ack resolves the barrier — actors never acked.
    for p in worker_pods:
        rec = CheckpointRecord(status=CheckpointRecordStatus(
            step=40, progress_step=40, barrier_id=notice["barrier"]))
        rec.metadata.name = p.metadata.name
        rec.metadata.namespace = NS
        rec.metadata.labels = {constants.LABEL_JOB_NAME: "rlb"}
        store.create(store_mod.CHECKPOINTRECORDS, rec)
    assert ckpt.ready_to_evict(NS, "rlb", "drain") is True
    assert ckpt.committed_step(NS, "rlb") == 40
    # Heterogeneous jobs publish the learner goodput lane; nothing was
    # lost (full ack), so the ratio is 1.0.
    assert metrics.learner_goodput_ratio.value(
        job_namespace=NS, job="rlb") == 1.0


# --- health: the actor lane -----------------------------------------------

def _health_env(store, job, bad_pods_spec, good_pods_spec):
    """Nodes node-ok/node-bad + the given pods; returns the recorder."""
    job.spec.run_policy.health_policy = HealthPolicy(enabled=True)
    store.create(store_mod.TPUJOBS, job)
    make_group(store, job.metadata.name, namespace=job.metadata.namespace)
    for name, healthy in (("node-ok", True), ("node-bad", False)):
        node = Node(spec=NodeSpec(chips=8),
                    status=NodeStatus(phase="Ready"))
        node.metadata.name = name
        if not healthy:
            node.status.conditions = {"MaintenancePending": "True"}
        store.create(store_mod.NODES, node)
    for rtype, idx in good_pods_spec:
        add_pod(store, job, rtype, idx, node="node-ok")
    for rtype, idx in bad_pods_spec:
        add_pod(store, job, rtype, idx, node="node-bad")
    recorder = Recorder()
    gang = SliceGangScheduler(store, total_chips=None)
    health = SliceHealthController(store, client=None, gang=gang,
                                   recorder=recorder)
    return health, recorder


def test_health_evicts_actors_without_gang_drain():
    store = Store()
    ns = "rl-health"
    job = make_rl_job(worker=2, actor=2, name="rlh", namespace=ns)
    health, recorder = _health_env(
        store, job,
        bad_pods_spec=[(ReplicaType.ACTOR, 0), (ReplicaType.ACTOR, 1)],
        good_pods_spec=[(ReplicaType.WORKER, 0), (ReplicaType.WORKER, 1)])
    before = metrics.actor_preemptions.value(job_namespace=ns,
                                             reason="health")
    health.health_pass()
    # Actors on the bad node deleted per-pod; the learner gang, its
    # group phase, and its pods are untouched — no drain, no barrier.
    live = {p.metadata.name for p in store.list(store_mod.PODS,
                                                namespace=ns)}
    assert live == {"rlh-worker-0", "rlh-worker-1"}
    group = store.get(store_mod.SLICEGROUPS, ns, "rlh")
    assert group.status.phase == PHASE_RUNNING
    assert recorder.events_for("rlh", REASON_ACTOR_EVICTED)
    assert not recorder.events_for("rlh", REASON_SLICE_DRAINED)
    assert metrics.actor_preemptions.value(
        job_namespace=ns, reason="health") == before + 2


def test_health_ignore_class_pods_are_left_alone():
    store = Store()
    ns = "rl-ignore"
    job = make_rl_job(worker=1, actor=1, name="rli", namespace=ns,
                      policy=actor_policy(
                          disruption=DisruptionClass.IGNORE))
    health, recorder = _health_env(
        store, job,
        bad_pods_spec=[(ReplicaType.ACTOR, 0)],
        good_pods_spec=[(ReplicaType.WORKER, 0)])
    health.health_pass()
    live = {p.metadata.name for p in store.list(store_mod.PODS,
                                                namespace=ns)}
    assert live == {"rli-worker-0", "rli-actor-0"}
    assert not recorder.events_for("rli", REASON_ACTOR_EVICTED)
    assert not recorder.events_for("rli", REASON_SLICE_DRAINED)


def test_learner_on_bad_node_takes_the_drain_path():
    """A learner sharing the degraded node disqualifies the actor lane:
    the gang goes down the existing atomic-drain path unchanged."""
    store = Store()
    ns = "rl-drain"
    job = make_rl_job(worker=2, actor=1, name="rld", namespace=ns)
    health, recorder = _health_env(
        store, job,
        bad_pods_spec=[(ReplicaType.WORKER, 1), (ReplicaType.ACTOR, 0)],
        good_pods_spec=[(ReplicaType.WORKER, 0)])
    health.health_pass()
    assert recorder.events_for("rld", REASON_SLICE_DRAINED)
    assert store.list(store_mod.PODS, namespace=ns) == []


# --- e2e: the actor kill storm --------------------------------------------

def test_e2e_actor_kill_storm_preserves_learner_world():
    """Mid-train, >= 50% of the actor pool is deleted at once. The
    engine recreates the pool (fresh uids) while every learner pod
    keeps its uid AND its bootstrap-hash annotation, and the job's
    committed step never moves — the heterogeneous-gang acceptance
    invariant (docs/rl.md), here against the real controller loop."""
    ns = "rl-e2e"
    store = Store()
    ckpt = CheckpointCoordinator(store)
    gang = SliceGangScheduler(store, total_chips=None, ckpt=ckpt)
    controller = TPUJobController(
        store, config=EngineConfig(enable_gang_scheduling=True),
        gang=gang, namespace=ns, ckpt=ckpt)
    controller.run(threadiness=2)
    try:
        job = make_rl_job(worker=2, actor=4, name="storm", namespace=ns,
                          ckpt=True)
        job = store.create(store_mod.TPUJOBS, job)
        wait_for(lambda: store.count(store_mod.PODS) >= 6,
                 msg="gang creation")

        def pods(rtype):
            return {p.metadata.name: p for p in store.list(
                store_mod.PODS, namespace=ns)
                if p.metadata.labels.get(
                    constants.LABEL_REPLICA_TYPE) == rtype}

        learners = pods("worker")
        assert len(learners) == 2 and len(pods("actor")) == 4
        world_before = {
            name: (p.metadata.uid, p.metadata.annotations.get(
                constants.ANNOTATION_BOOTSTRAP_HASH))
            for name, p in learners.items()}
        assert all(h for _, h in world_before.values())

        # Mid-train state: learners have committed step 30.
        for name in learners:
            rec = CheckpointRecord(status=CheckpointRecordStatus(
                step=30, progress_step=30))
            rec.metadata.name = name
            rec.metadata.namespace = ns
            rec.metadata.labels = {constants.LABEL_JOB_NAME: "storm"}
            store.create(store_mod.CHECKPOINTRECORDS, rec)
        assert ckpt.committed_step(ns, "storm") == 30

        # THE STORM: half the pool, one shot.
        doomed = sorted(pods("actor"))[:2]
        killed_uids = {n: pods("actor")[n].metadata.uid for n in doomed}
        for name in doomed:
            store.try_delete(store_mod.PODS, ns, name)

        def pool_healed():
            actors = pods("actor")
            return (len(actors) == 4
                    and all(actors[n].metadata.uid != killed_uids[n]
                            for n in doomed if n in actors))

        wait_for(pool_healed, msg="actor pool heal")

        # The learner world never noticed: same uids, same bootstrap
        # hashes, same committed step — no restart, no rollback.
        learners_after = pods("worker")
        assert {
            name: (p.metadata.uid, p.metadata.annotations.get(
                constants.ANNOTATION_BOOTSTRAP_HASH))
            for name, p in learners_after.items()} == world_before
        assert ckpt.committed_step(ns, "storm") == 30
        # Recreated actors got the fresh learner-endpoint env.
        for name, p in pods("actor").items():
            env = p.spec.containers[0].env
            assert constants.ENV_LEARNER_ENDPOINTS in env
            assert not any(k.startswith("JAX_") for k in env)
    finally:
        controller.stop()
        store.stop_watchers()


# CI shard (pyproject [tool.pytest.ini_options] markers)
pytestmark = pytest.mark.control_plane
