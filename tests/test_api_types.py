"""API type round-trip + helper tests (reference: util_test.go, types)."""

import datetime as dt

from tf_operator_tpu.api import constants
from tf_operator_tpu.api.serde import parse_time, snake_to_camel
from tf_operator_tpu.api.types import (
    Container,
    JobCondition,
    ObjectMeta,
    Pod,
    PodPhase,
    ReplicaStatus,
    ReplicaType,
    TPUJob,
    gen_general_name,
    is_chief_or_master,
    is_evaluator,
    is_worker,
)
from tf_operator_tpu import testutil


def test_snake_to_camel():
    assert snake_to_camel("replica_specs") == "replicaSpecs"
    assert snake_to_camel("ttl_seconds_after_finished") == "ttlSecondsAfterFinished"
    assert snake_to_camel("name") == "name"


def test_gen_general_name():
    # Reference contract {job}-{rtype}-{index} (common/util.go:47-50) —
    # pod_names_validation_tests.py asserts this naming e2e.
    assert gen_general_name("mnist", "Worker", 3) == "mnist-worker-3"
    assert gen_general_name("j", ReplicaType.PS, 0) == "j-ps-0"


def test_role_helpers():
    assert is_chief_or_master("chief")
    assert is_chief_or_master("Master")
    assert not is_chief_or_master("worker")
    assert is_worker("Worker")
    assert is_evaluator("evaluator")


def test_job_round_trip():
    job = testutil.new_tpujob(worker=4, ps=2, accelerator="v5p-32")
    job.status.replica_statuses["worker"] = ReplicaStatus(active=3, failed=1)
    job.status.conditions.append(JobCondition(
        type="Created", status="True", reason="JobCreated",
        last_update_time=dt.datetime(2026, 1, 1, tzinfo=dt.timezone.utc)))
    wire = job.to_dict()
    assert wire["apiVersion"] == constants.API_VERSION
    assert wire["spec"]["replicaSpecs"]["worker"]["replicas"] == 4
    assert wire["spec"]["slice"]["accelerator"] == "v5p-32"
    assert wire["status"]["conditions"][0]["lastUpdateTime"] == "2026-01-01T00:00:00Z"

    back = TPUJob.from_dict(wire)
    assert back.spec.replica_specs["worker"].replicas == 4
    assert back.status.replica_statuses["worker"].active == 3
    assert back.status.conditions[0].last_update_time.year == 2026
    assert back.to_dict() == wire


def test_pod_round_trip():
    job = testutil.new_tpujob(worker=1)
    pod = testutil.new_pod(job, "worker", 0, phase=PodPhase.FAILED, exit_code=137)
    wire = pod.to_dict()
    back = Pod.from_dict(wire)
    assert back.status.phase == "Failed"
    assert back.status.container_statuses[0].exit_code == 137
    assert back.metadata.controller_ref().uid == job.metadata.uid
    assert back.metadata.labels[constants.LABEL_REPLICA_INDEX] == "0"


def test_deepcopy_isolation():
    job = testutil.new_tpujob(worker=2)
    cp = job.deepcopy()
    cp.spec.replica_specs["worker"].replicas = 99
    assert job.spec.replica_specs["worker"].replicas == 2


def test_parse_time_accepts_offsets():
    t = parse_time("2026-07-29T10:00:00+02:00")
    assert t.utcoffset() == dt.timedelta(hours=2)


def test_container_defaults():
    c = Container()
    assert c.name == constants.DEFAULT_CONTAINER_NAME
    m = ObjectMeta()
    assert m.namespace == "default"
    assert m.controller_ref() is None

# CI shard (pyproject [tool.pytest.ini_options] markers)
import pytest  # noqa: E402
pytestmark = pytest.mark.control_plane
