"""Slice-health & auto-repair tests (controller/health.py).

Unit level drives ``health_pass`` directly against the Store (cordon,
grace windows, policy gating, atomic drain, displaced re-queue
ordering); the e2e tier runs the full repair loop on the kube backend
against the fake apiserver: injected maintenance event under a running
1c+4w gang -> cordon -> atomic slice drain -> re-admission -> rebind on
spare capacity -> resume via restart-with-identity, with the drain
events and slice_drains/time-to-rebind metrics observable. A control
test pins that a job without a HealthPolicy is left untouched.
"""

import datetime as dt
import time

import pytest

from tf_operator_tpu.api import constants
from tf_operator_tpu.api.types import (
    Container,
    HealthPolicy,
    JobConditionType,
    Node,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
    Pod,
    PodSpec,
    PodTemplateSpec,
    ReplicaSpec,
    RestartPolicy,
    RunPolicy,
    SliceGroup,
    SliceGroupSpec,
    SliceGroupStatus,
    TPUJob,
    TPUJobSpec,
    TPUSliceSpec,
)
from tf_operator_tpu.controller.gang import (
    PHASE_INQUEUE,
    PHASE_PENDING,
    PHASE_RUNNING,
    SliceGangScheduler,
)
from tf_operator_tpu.controller.health import (
    COND_MAINTENANCE,
    COND_TERMINATION,
    NODE_DEGRADED,
    NODE_DRAINING,
    NODE_HEALTHY,
    SliceHealthController,
    classify_node,
    node_maintenance_pending,
)
from tf_operator_tpu.runtime import metrics, store as store_mod
from tf_operator_tpu.runtime.events import (
    REASON_NODE_CORDONED,
    REASON_SLICE_DRAIN_PENDING,
    REASON_SLICE_DRAINED,
    REASON_SLICE_REBOUND,
    Recorder,
)
from tf_operator_tpu.runtime.store import Store


def _now():
    return dt.datetime.now(dt.timezone.utc)


def make_node(name, chips=8, domain="", phase="Ready", unschedulable=False,
              conditions=None) -> Node:
    labels = {constants.LABEL_ICI_DOMAIN: domain} if domain else {}
    return Node(
        metadata=ObjectMeta(name=name, namespace="", labels=labels),
        spec=NodeSpec(chips=chips, unschedulable=unschedulable),
        status=NodeStatus(phase=phase, conditions=dict(conditions or {})))


def add_node(store, **kw) -> Node:
    return store.create(store_mod.NODES, make_node(**kw))


def add_job(store, name, health=None, accelerator="v5e-8",
            workers=1) -> TPUJob:
    job = TPUJob(metadata=ObjectMeta(name=name, namespace="default"))
    job.spec = TPUJobSpec(
        replica_specs={"worker": ReplicaSpec(
            replicas=workers,
            template=PodTemplateSpec(spec=PodSpec(containers=[
                Container(name=constants.DEFAULT_CONTAINER_NAME)])),
            restart_policy=RestartPolicy.NEVER)},
        run_policy=RunPolicy(health_policy=health),
        slice=TPUSliceSpec(accelerator=accelerator))
    return store.create(store_mod.TPUJOBS, job)


def add_group(store, name, chips=8, phase=PHASE_PENDING,
              age_seconds=0.0, min_member=1) -> SliceGroup:
    group = SliceGroup(
        spec=SliceGroupSpec(min_member=min_member,
                            slice=TPUSliceSpec(
                                accelerator=f"v5e-{chips}")),
        status=SliceGroupStatus(
            phase=phase,
            pending_since=_now() - dt.timedelta(seconds=age_seconds)))
    group.metadata.name = name
    group.metadata.namespace = "default"
    group.metadata.creation_timestamp = \
        _now() - dt.timedelta(seconds=age_seconds)
    return store.create(store_mod.SLICEGROUPS, group)


def add_pod(store, group, index=0, node="", phase="Running",
            chips=8) -> Pod:
    pod = Pod(spec=PodSpec(
        containers=[Container(
            resources={constants.RESOURCE_TPU: str(chips)})],
        scheduler_name=constants.DEFAULT_GANG_SCHEDULER,
        node_name=node))
    pod.metadata.name = f"{group}-worker-{index}"
    pod.metadata.namespace = "default"
    pod.metadata.labels = {
        constants.LABEL_JOB_NAME: group,
        constants.LABEL_REPLICA_TYPE: "worker",
        constants.LABEL_REPLICA_INDEX: str(index),
    }
    pod.metadata.annotations = {
        constants.ANNOTATION_GANG_GROUP: group,
        constants.ANNOTATION_GANG_TASK: "worker",
    }
    pod.status.phase = phase
    return store.create(store_mod.PODS, pod)


@pytest.fixture
def store():
    return Store()


@pytest.fixture
def gang(store):
    return SliceGangScheduler(store, total_chips=None)


@pytest.fixture
def recorder():
    return Recorder()


@pytest.fixture
def health(store, gang, recorder):
    # client=None: cordon via the store; pod_control=None: store deletes.
    return SliceHealthController(store, client=None, gang=gang,
                                 recorder=recorder)


def node_of(store, name):
    return store.get(store_mod.NODES, "", name)


def group_phase(store, name):
    return store.get(store_mod.SLICEGROUPS, "default", name).status.phase


def pod_names(store):
    return {p.metadata.name for p in store.list(store_mod.PODS)}


# ---------------------------------------------------------------------------
# Classification
# ---------------------------------------------------------------------------

class TestClassification:
    def test_healthy_node(self):
        n = make_node(name="n1", conditions={"Ready": "True"})
        assert classify_node(n) == (NODE_HEALTHY, "")
        assert not node_maintenance_pending(n)

    def test_not_ready_is_degraded(self):
        n = make_node(name="n1", phase="NotReady")
        assert classify_node(n) == (NODE_DEGRADED, "NotReady")

    def test_maintenance_pending_is_degraded(self):
        n = make_node(name="n1",
                      conditions={"Ready": "True",
                                  COND_MAINTENANCE: "True"})
        assert classify_node(n) == (NODE_DEGRADED, COND_MAINTENANCE)
        assert node_maintenance_pending(n)

    def test_termination_outranks_maintenance(self):
        n = make_node(name="n1",
                      conditions={COND_MAINTENANCE: "True",
                                  COND_TERMINATION: "True"})
        assert classify_node(n) == (NODE_DEGRADED, COND_TERMINATION)

    def test_cordoned_with_signal_is_draining(self):
        n = make_node(name="n1", unschedulable=True,
                      conditions={COND_MAINTENANCE: "True"})
        assert classify_node(n) == (NODE_DRAINING, COND_MAINTENANCE)

    def test_admin_cordon_without_signal_stays_healthy(self):
        n = make_node(name="n1", unschedulable=True,
                      conditions={"Ready": "True"})
        assert classify_node(n) == (NODE_HEALTHY, "")


# ---------------------------------------------------------------------------
# Cordoning
# ---------------------------------------------------------------------------

class TestCordon:
    def test_maintenance_node_cordoned_with_event_and_metric(
            self, store, health, recorder):
        before = metrics.nodes_cordoned.value(reason=COND_MAINTENANCE)
        add_node(store, name="n1",
                 conditions={"Ready": "True", COND_MAINTENANCE: "True"})
        health.health_pass()
        assert node_of(store, "n1").spec.unschedulable
        assert metrics.nodes_cordoned.value(
            reason=COND_MAINTENANCE) == before + 1
        assert recorder.events_for("n1", REASON_NODE_CORDONED)

    def test_cordon_is_idempotent_across_passes(self, store, health):
        before = metrics.nodes_cordoned.value(reason=COND_TERMINATION)
        add_node(store, name="n1",
                 conditions={"Ready": "True", COND_TERMINATION: "True"})
        health.health_pass()
        health.health_pass()
        # Second pass sees Draining (already cordoned): no re-cordon.
        assert metrics.nodes_cordoned.value(
            reason=COND_TERMINATION) == before + 1

    def test_not_ready_node_is_not_cordoned(self, store, health):
        # A kubelet blip must not leave a permanent cordon; NotReady is
        # already out of capacity via the schedulability predicate.
        add_node(store, name="n1", phase="NotReady")
        health.health_pass()
        assert not node_of(store, "n1").spec.unschedulable

    def test_healthy_node_untouched(self, store, health):
        add_node(store, name="n1", conditions={"Ready": "True"})
        health.health_pass()
        assert not node_of(store, "n1").spec.unschedulable


# ---------------------------------------------------------------------------
# Gang drain
# ---------------------------------------------------------------------------

def _gang_on_degraded_node(store, policy, group="j1",
                           signal=COND_MAINTENANCE):
    """A 2-worker gang running across one degraded + one healthy node."""
    add_node(store, name="bad", domain="d1",
             conditions={"Ready": "True", signal: "True"})
    add_node(store, name="ok", domain="d1",
             conditions={"Ready": "True"})
    add_node(store, name="spare", domain="d2",
             conditions={"Ready": "True"})
    add_job(store, group, health=policy, accelerator="v5e-16", workers=2)
    add_group(store, group, chips=16, phase=PHASE_RUNNING, min_member=2)
    add_pod(store, group, index=0, node="bad")
    add_pod(store, group, index=1, node="ok")


class TestDrain:
    def test_atomic_drain_evicts_whole_gang_and_displaces(
            self, store, health, recorder):
        drains = metrics.slice_drains.value(job_namespace="default")
        _gang_on_degraded_node(store, HealthPolicy(enabled=True))
        health.health_pass()
        # BOTH pods evicted — the member on the healthy node too (it
        # would pin the slice to the degraded domain otherwise).
        assert pod_names(store) == set()
        sg = store.get(store_mod.SLICEGROUPS, "default", "j1")
        # Displaced through Pending; the fixture's unlimited capacity
        # re-admits it in the same displace() call, so Inqueue is the
        # legal steady state here — Running is not.
        assert sg.status.phase in (PHASE_PENDING, PHASE_INQUEUE)
        assert COND_MAINTENANCE in sg.status.displaced_reason
        assert sg.status.pending_since is not None
        assert metrics.slice_drains.value(
            job_namespace="default") == drains + 1
        assert recorder.events_for("j1", REASON_SLICE_DRAINED)

    def test_no_policy_leaves_gang_untouched(self, store, health):
        _gang_on_degraded_node(store, None)
        health.health_pass()
        assert pod_names(store) == {"j1-worker-0", "j1-worker-1"}
        assert group_phase(store, "j1") == PHASE_RUNNING
        # The node still gets cordoned (operator-wide hygiene).
        assert node_of(store, "bad").spec.unschedulable

    def test_disabled_policy_leaves_gang_untouched(self, store, health):
        _gang_on_degraded_node(store, HealthPolicy(enabled=False))
        health.health_pass()
        assert pod_names(store) == {"j1-worker-0", "j1-worker-1"}
        assert group_phase(store, "j1") == PHASE_RUNNING

    def test_handle_maintenance_off_ignores_advance_notice(
            self, store, health):
        _gang_on_degraded_node(
            store, HealthPolicy(enabled=True, handle_maintenance=False))
        health.health_pass()
        assert pod_names(store) == {"j1-worker-0", "j1-worker-1"}
        assert group_phase(store, "j1") == PHASE_RUNNING

    def test_handle_maintenance_off_still_drains_termination(
            self, store, health):
        _gang_on_degraded_node(
            store, HealthPolicy(enabled=True, handle_maintenance=False),
            signal=COND_TERMINATION)
        health.health_pass()
        assert pod_names(store) == set()
        assert group_phase(store, "j1") in (PHASE_PENDING, PHASE_INQUEUE)

    def test_not_ready_node_drains_opted_in_gang(self, store, health):
        add_node(store, name="bad", domain="d1", phase="NotReady")
        add_job(store, "j1", health=HealthPolicy(enabled=True))
        add_group(store, "j1", phase=PHASE_RUNNING)
        add_pod(store, "j1", index=0, node="bad")
        health.health_pass()
        assert pod_names(store) == set()
        assert group_phase(store, "j1") in (PHASE_PENDING, PHASE_INQUEUE)

    def test_grace_window_delays_then_drains(self, store, health,
                                             recorder):
        _gang_on_degraded_node(
            store,
            HealthPolicy(enabled=True, drain_grace_seconds=60.0))
        health.health_pass()
        # In grace: warned once, nothing evicted.
        assert pod_names(store) == {"j1-worker-0", "j1-worker-1"}
        assert recorder.events_for("j1", REASON_SLICE_DRAIN_PENDING)
        # Age the episode past the grace and pass again: drains.
        health._drain_first_seen[("default", "j1")] -= 120.0
        health.health_pass()
        assert pod_names(store) == set()
        assert group_phase(store, "j1") in (PHASE_PENDING, PHASE_INQUEUE)

    def test_signal_clearing_in_grace_cancels_drain(self, store, health):
        _gang_on_degraded_node(
            store,
            HealthPolicy(enabled=True, drain_grace_seconds=60.0))
        health.health_pass()
        assert ("default", "j1") in health._drain_first_seen
        # Maintenance cancelled: condition clears before the grace ends.
        node = node_of(store, "bad")
        node.status.conditions[COND_MAINTENANCE] = "False"
        store.update(store_mod.NODES, node)
        health.health_pass()
        assert ("default", "j1") not in health._drain_first_seen
        assert pod_names(store) == {"j1-worker-0", "j1-worker-1"}

    def test_operator_default_grace_applies_when_policy_unset(
            self, store, gang, recorder):
        health = SliceHealthController(store, gang=gang,
                                       recorder=recorder,
                                       default_grace_seconds=60.0)
        _gang_on_degraded_node(store, HealthPolicy(enabled=True))
        health.health_pass()
        assert pod_names(store) == {"j1-worker-0", "j1-worker-1"}

    def test_rebind_observed_with_histogram_and_event(
            self, store, health, gang, recorder):
        hist_before = metrics.drain_rebind_seconds._totals.get(
            ("default",), 0)
        _gang_on_degraded_node(store, HealthPolicy(enabled=True))
        health.health_pass()
        assert group_phase(store, "j1") in (PHASE_PENDING, PHASE_INQUEUE)
        # Repair arc: group re-admitted, pods recreated AND bound on the
        # spare domain (what engine + binder do on the real backends).
        sg = store.get(store_mod.SLICEGROUPS, "default", "j1")
        sg.status.phase = PHASE_INQUEUE
        store.update_status(store_mod.SLICEGROUPS, sg)
        add_pod(store, "j1", index=0, node="spare", phase="Pending")
        add_pod(store, "j1", index=1, node="spare", phase="Pending")
        health.health_pass()
        assert ("default", "j1") not in health._rebind_started
        assert metrics.drain_rebind_seconds._totals.get(
            ("default",), 0) == hist_before + 1
        assert recorder.events_for("j1", REASON_SLICE_REBOUND)

    def test_rebind_not_observed_while_gated_or_on_degraded(
            self, store, health):
        _gang_on_degraded_node(store, HealthPolicy(enabled=True))
        health.health_pass()
        # Still Pending: stopwatch stays open.
        health.health_pass()
        assert ("default", "j1") in health._rebind_started


# ---------------------------------------------------------------------------
# Displaced re-queue ordering (gang.displace contract)
# ---------------------------------------------------------------------------

class TestDisplacedOrdering:
    def test_displaced_group_readmits_ahead_of_equal_priority_newcomer(
            self, store):
        """A drained group keeps its creation timestamp, so when
        capacity fits only one group it wins the FIFO tiebreak against
        an equal-priority newcomer that arrived while it ran."""
        gang = SliceGangScheduler(store, total_chips=8)
        add_group(store, "displaced", chips=8, phase=PHASE_RUNNING,
                  age_seconds=600.0)
        assert gang.displace("default", "displaced", "node degraded")
        # Newcomer appeared after the original admission.
        add_group(store, "newcomer", chips=8, age_seconds=1.0)
        gang.readmit()
        assert group_phase(store, "displaced") == PHASE_INQUEUE
        assert group_phase(store, "newcomer") == PHASE_PENDING

    def test_displace_resets_pending_since_for_fresh_aging(self, store):
        gang = SliceGangScheduler(store, total_chips=8)
        add_group(store, "g", chips=8, phase=PHASE_RUNNING,
                  age_seconds=600.0)
        before = _now()
        assert gang.displace("default", "g", "why")
        sg = store.get(store_mod.SLICEGROUPS, "default", "g")
        assert sg.status.pending_since >= before
        assert sg.status.displaced_reason == "why"

    def test_displace_pending_group_is_noop(self, store):
        gang = SliceGangScheduler(store, total_chips=8)
        add_group(store, "g", chips=8, phase=PHASE_PENDING)
        assert not gang.displace("default", "g", "why")

    def test_promotion_clears_displaced_reason(self, store):
        """Once the rebound gang is fully up, the displaced marker (and
        with it the job's Restarting condition) clears."""
        gang = SliceGangScheduler(store, total_chips=16)
        add_group(store, "g", chips=8, phase=PHASE_RUNNING, min_member=1)
        gang.displace("default", "g", "node degraded")
        gang.readmit()
        assert group_phase(store, "g") == PHASE_INQUEUE
        # Promotion of a displaced group verifies LIVE pod state (the
        # job tallies are stale right after an eviction), so a real
        # Running pod must exist in the store.
        add_pod(store, "g", index=0, node="n1", phase="Running")
        job = add_job(store, "g")
        job.status.replica_statuses = {}
        from tf_operator_tpu.api.types import ReplicaStatus

        job.status.replica_statuses["worker"] = ReplicaStatus(active=1)
        sg = store.get(store_mod.SLICEGROUPS, "default", "g")
        gang._maybe_promote_running(sg, job)
        sg = store.get(store_mod.SLICEGROUPS, "default", "g")
        assert sg.status.phase == PHASE_RUNNING
        assert sg.status.displaced_reason == ""
        assert gang.displaced_reason(job) is None


# ---------------------------------------------------------------------------
# E2E on the kube backend: the full repair loop
# ---------------------------------------------------------------------------

from tf_operator_tpu.runtime.kube import (  # noqa: E402
    KubeClient,
    KubeConfig,
    KubeOperator,
)
from tf_operator_tpu.runtime.kube_fake import FakeKubeApiServer  # noqa: E402


def wait_for(cond, timeout=20.0, interval=0.05, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        result = cond()
        if result:
            return result
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture
def fake():
    with FakeKubeApiServer() as server:
        yield server


@pytest.fixture
def client(fake):
    return KubeClient(KubeConfig(server=fake.url))


def kube_gang_job(name, health=None):
    """1 chief + 4 workers over a v5e-16 x 2 multislice (2 hosts x 8
    chips per slice)."""
    job = TPUJob(metadata=ObjectMeta(name=name, namespace="default"))
    template = PodTemplateSpec(spec=PodSpec(containers=[
        Container(name=constants.DEFAULT_CONTAINER_NAME,
                  image="tpu-worker:latest",
                  command=["python", "-m", "train"])]))
    job.spec = TPUJobSpec(
        replica_specs={
            "chief": ReplicaSpec(replicas=1,
                                 template=template.deepcopy(),
                                 restart_policy=RestartPolicy.NEVER),
            "worker": ReplicaSpec(replicas=4,
                                  template=template.deepcopy(),
                                  restart_policy=RestartPolicy.NEVER),
        },
        run_policy=RunPolicy(health_policy=health),
        slice=TPUSliceSpec(accelerator="v5e-16", num_slices=2))
    from tf_operator_tpu.runtime.kube import tpujob_to_k8s

    return tpujob_to_k8s(job)


def _node_of(fake, ns, name):
    pod = fake.state.objects["pods"].get((ns, name))
    return ((pod or {}).get("spec") or {}).get("nodeName", "")


def _pod_uid(fake, ns, name):
    pod = fake.state.objects["pods"].get((ns, name))
    return ((pod or {}).get("metadata") or {}).get("uid", "")


ALL_PODS = [f"hj-worker-{i}" for i in range(4)] + ["hj-chief-0"]


class TestHealthE2E:
    """The acceptance loop: injected maintenance event under a running
    1c+4w gang -> cordon -> atomic slice drain -> re-admission -> rebind
    on spare nodes -> resume, with no pod left on the degraded node."""

    def _cluster(self, fake):
        # Three ICI domains x two 8-chip hosts: 48 chips; the job uses
        # 32, leaving one spare domain to absorb a drained slice.
        for dom in ("dom-a", "dom-b", "dom-c"):
            for i in range(2):
                fake.state.add_node(f"{dom}-n{i}", chips=8,
                                    ici_domain=dom)

    def _wait_all_bound(self, fake, msg):
        def all_bound():
            nodes = [_node_of(fake, "default", n) for n in ALL_PODS]
            return nodes if all(nodes) else None
        return wait_for(all_bound, timeout=25, msg=msg)

    def test_maintenance_event_cordon_drain_rebind_resume(
            self, client, fake):
        drains = metrics.slice_drains.value(job_namespace="default")
        hist = metrics.drain_rebind_seconds._totals.get(("default",), 0)
        self._cluster(fake)
        op = KubeOperator(client, post_events=False,
                          enable_gang_scheduling=True)
        op.start(threadiness=1, sync_timeout=10)
        try:
            assert op.health is not None  # wired by default
            fake.state.create(
                constants.PLURAL, "default",
                kube_gang_job("hj", health=HealthPolicy(enabled=True)))
            self._wait_all_bound(fake, "gang bound")
            fake.state.set_all_pods_phase(
                "default", "Running",
                selector={constants.LABEL_JOB_NAME: "hj"})
            wait_for(lambda: (op.store.try_get(
                store_mod.SLICEGROUPS, "default", "hj") or
                SliceGroup()).status.phase == PHASE_RUNNING,
                msg="gang promoted Running")

            # A worker's node gets a maintenance notice.
            victim_node = _node_of(fake, "default", "hj-worker-0")
            assert victim_node
            old_uids = {n: _pod_uid(fake, "default", n) for n in ALL_PODS}
            fake.state.inject_maintenance(victim_node)

            # Cordon lands on the API server.
            wait_for(lambda: (fake.state.objects["nodes"]
                              [("", victim_node)].get("spec") or {})
                     .get("unschedulable"), msg="node cordoned")

            # Atomic drain + rebind: every pod recreated (fresh uid) and
            # bound, none on the degraded node.
            def rebound():
                for n in ALL_PODS:
                    node = _node_of(fake, "default", n)
                    if (not node or node == victim_node
                            or _pod_uid(fake, "default", n)
                            == old_uids[n]):
                        return False
                return True
            wait_for(rebound, timeout=25,
                     msg="gang rebound on spare capacity")

            # Slices stayed whole per ICI domain after the rebind.
            doms = [
                _node_of(fake, "default",
                         f"hj-worker-{i}").rsplit("-n", 1)[0]
                for i in range(4)]
            assert len({doms[0], doms[1]}) == 1, doms
            assert len({doms[2], doms[3]}) == 1, doms

            # Restart-with-identity surfaced on the job while rebinding.
            wait_for(lambda: any(
                c.get("type") == JobConditionType.RESTARTING
                and c.get("status") == "True"
                for c in (client.get(store_mod.TPUJOBS, "default", "hj")
                          .get("status") or {}).get("conditions") or []),
                msg="Restarting condition on job")

            # Kubelet reports the rebound gang Running: job resumes.
            fake.state.set_all_pods_phase(
                "default", "Running",
                selector={constants.LABEL_JOB_NAME: "hj"})
            wait_for(lambda: any(
                c.get("type") == JobConditionType.RUNNING
                and c.get("status") == "True"
                for c in (client.get(store_mod.TPUJOBS, "default", "hj")
                          .get("status") or {}).get("conditions") or []),
                msg="job Running again after repair")

            # Drain observability: metric bumped, rebind latency
            # histogram closed, events recorded.
            assert metrics.slice_drains.value(
                job_namespace="default") == drains + 1
            wait_for(lambda: metrics.drain_rebind_seconds._totals.get(
                ("default",), 0) == hist + 1,
                msg="time-to-rebind observed")
            reasons = {e.reason for e in
                       op.controller.recorder.events}
            assert REASON_NODE_CORDONED in reasons
            assert REASON_SLICE_DRAINED in reasons
        finally:
            op.stop()

    def test_control_disabled_policy_gang_untouched(self, client, fake):
        """Same maintenance event, no HealthPolicy: the node is
        cordoned (operator-wide hygiene) but the gang keeps running,
        bound where it was."""
        self._cluster(fake)
        op = KubeOperator(client, post_events=False,
                          enable_gang_scheduling=True)
        op.start(threadiness=1, sync_timeout=10)
        try:
            fake.state.create(constants.PLURAL, "default",
                              kube_gang_job("hj", health=None))
            before = self._wait_all_bound(fake, "gang bound")
            fake.state.set_all_pods_phase(
                "default", "Running",
                selector={constants.LABEL_JOB_NAME: "hj"})
            victim_node = _node_of(fake, "default", "hj-worker-0")
            old_uids = {n: _pod_uid(fake, "default", n) for n in ALL_PODS}
            fake.state.inject_maintenance(victim_node)
            wait_for(lambda: (fake.state.objects["nodes"]
                              [("", victim_node)].get("spec") or {})
                     .get("unschedulable"), msg="node cordoned")
            time.sleep(2.0)  # give a wrong drain time to land
            after = [_node_of(fake, "default", n) for n in ALL_PODS]
            assert after == before
            assert all(_pod_uid(fake, "default", n) == old_uids[n]
                       for n in ALL_PODS)
            sg = op.store.try_get(store_mod.SLICEGROUPS, "default", "hj")
            assert sg is not None and sg.status.phase == PHASE_RUNNING
        finally:
            op.stop()

    def test_slice_health_can_be_disabled(self, client, fake):
        op = KubeOperator(client, post_events=False,
                          enable_gang_scheduling=True,
                          slice_health=False)
        try:
            assert op.health is None
        finally:
            op.stop()


# CI shard (pyproject [tool.pytest.ini_options] markers)
pytestmark = pytest.mark.control_plane
