"""Exit-code policy tests (reference train_util.go:18-53)."""

import pytest

from tf_operator_tpu.controller.exit_codes import is_retryable_exit_code


@pytest.mark.parametrize("code", [1, 2, 126, 127, 128, 139])
def test_permanent(code):
    assert not is_retryable_exit_code(code)


@pytest.mark.parametrize("code", [130, 137, 143, 138])
def test_retryable(code):
    assert is_retryable_exit_code(code)


@pytest.mark.parametrize("code", [0, 3, 42, 100, 255])
def test_unknown_treated_permanent(code):
    assert not is_retryable_exit_code(code)

# CI shard (pyproject [tool.pytest.ini_options] markers)
pytestmark = pytest.mark.control_plane
