"""Manifests and examples stay consistent with the API surface.

- schema codegen-verify (hack/verify-codegen.sh analog);
- every example TPUJob YAML parses, validates (schema + semantic
  validation), and round-trips through the wire format.
"""

import glob
import json
import os
import sys

import jsonschema
import pytest
import yaml

from tf_operator_tpu import testutil
from tf_operator_tpu.api import set_defaults
from tf_operator_tpu.api.schema import generate_schema
from tf_operator_tpu.api.types import TPUJob
from tf_operator_tpu.api.validation import validate_job

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCHEMA_PATH = os.path.join(REPO, "manifests", "base", "tpujob.schema.json")
EXAMPLE_SPECS = sorted(glob.glob(os.path.join(REPO, "examples", "*",
                                              "tpujob_*.yaml")))


def test_checked_in_schema_matches_generated():
    with open(SCHEMA_PATH) as f:
        checked_in = json.load(f)
    assert checked_in == generate_schema(), (
        "manifests/base/tpujob.schema.json is stale; run "
        "python manifests/gen.py")


def test_generated_api_doc_fresh():
    sys.path.insert(0, os.path.join(REPO, "docs"))
    import gen_api

    with open(os.path.join(REPO, "docs", "api.md")) as f:
        assert f.read() == gen_api.render(), (
            "docs/api.md is stale; run python docs/gen_api.py")


def test_schema_accepts_real_jobs():
    schema = generate_schema()
    job = testutil.new_tpujob(worker=4, ps=2, chief=1)
    jsonschema.validate(job.to_dict(), schema)


def test_schema_rejects_malformed():
    schema = generate_schema()
    for bad in (
        {"spec": {"replicaSpecs": "not-a-map"}},
        {"spec": {"runPolicy": {"backoffLimit": "three"}}},
        {"metadata": {"name": 42}},
        {"unknownTopLevel": {}},
    ):
        with pytest.raises(jsonschema.ValidationError):
            jsonschema.validate(bad, schema)


def test_examples_exist():
    assert len(EXAMPLE_SPECS) >= 3


@pytest.mark.parametrize("path", EXAMPLE_SPECS,
                         ids=[os.path.basename(p) for p in EXAMPLE_SPECS])
def test_example_spec_valid(path):
    with open(path) as f:
        data = yaml.safe_load(f)
    jsonschema.validate(data, generate_schema())
    job = TPUJob.from_dict(data)
    set_defaults(job)
    validate_job(job)
    # wire round-trip is lossless
    assert TPUJob.from_dict(job.to_dict()).to_dict() == job.to_dict()
