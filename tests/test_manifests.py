"""Manifests and examples stay consistent with the API surface.

- schema codegen-verify (hack/verify-codegen.sh analog);
- every example TPUJob YAML parses, validates (schema + semantic
  validation), and round-trips through the wire format.
"""

import glob
import json
import os
import sys

import jsonschema
import pytest
import yaml

from tf_operator_tpu import testutil
from tf_operator_tpu.api import set_defaults
from tf_operator_tpu.api.schema import generate_schema
from tf_operator_tpu.api.types import TPUJob
from tf_operator_tpu.api.validation import validate_job

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCHEMA_PATH = os.path.join(REPO, "manifests", "base", "tpujob.schema.json")
EXAMPLE_SPECS = sorted(glob.glob(os.path.join(REPO, "examples", "*",
                                              "tpujob_*.yaml")))


def test_checked_in_schema_matches_generated():
    with open(SCHEMA_PATH) as f:
        checked_in = json.load(f)
    assert checked_in == generate_schema(), (
        "manifests/base/tpujob.schema.json is stale; run "
        "python manifests/gen.py")


def test_generated_api_doc_fresh():
    sys.path.insert(0, os.path.join(REPO, "docs"))
    import gen_api

    with open(os.path.join(REPO, "docs", "api.md")) as f:
        assert f.read() == gen_api.render(), (
            "docs/api.md is stale; run python docs/gen_api.py")


def test_schema_accepts_real_jobs():
    schema = generate_schema()
    job = testutil.new_tpujob(worker=4, ps=2, chief=1)
    jsonschema.validate(job.to_dict(), schema)


def test_schema_rejects_malformed():
    schema = generate_schema()
    for bad in (
        {"spec": {"replicaSpecs": "not-a-map"}},
        {"spec": {"runPolicy": {"backoffLimit": "three"}}},
        {"metadata": {"name": 42}},
        {"unknownTopLevel": {}},
    ):
        with pytest.raises(jsonschema.ValidationError):
            jsonschema.validate(bad, schema)


def test_examples_exist():
    assert len(EXAMPLE_SPECS) >= 3


@pytest.mark.parametrize("path", EXAMPLE_SPECS,
                         ids=[os.path.basename(p) for p in EXAMPLE_SPECS])
def test_example_spec_valid(path):
    with open(path) as f:
        data = yaml.safe_load(f)
    jsonschema.validate(data, generate_schema())
    job = TPUJob.from_dict(data)
    set_defaults(job)
    validate_job(job)
    # wire round-trip is lossless
    assert TPUJob.from_dict(job.to_dict()).to_dict() == job.to_dict()


def test_checked_in_crd_matches_generated():
    sys.path.insert(0, os.path.join(REPO, "manifests"))
    import gen as manifests_gen

    with open(os.path.join(REPO, "manifests", "base", "crd.yaml")) as f:
        assert f.read() == manifests_gen.render_crd(), (
            "manifests/base/crd.yaml is stale; run python manifests/gen.py")


def test_crd_schema_is_structural():
    """Kubernetes structural-schema rules: no $ref, every node typed,
    no additionalProperties alongside properties."""
    from tf_operator_tpu.api.schema import generate_crd_schema

    def walk(node, path="root"):
        assert "$ref" not in node, f"$ref at {path}"
        # A node is "typed" with an explicit type, the preserve-unknown
        # escape hatch, or the native IntOrString marker (all valid
        # structural-schema forms).
        assert (node.get("type")
                or "x-kubernetes-preserve-unknown-fields" in node
                or node.get("x-kubernetes-int-or-string")), \
            f"untyped node at {path}"
        assert not ("properties" in node and "additionalProperties" in node), \
            f"properties+additionalProperties at {path}"
        for key, child in (node.get("properties") or {}).items():
            walk(child, f"{path}.{key}")
        if isinstance(node.get("additionalProperties"), dict):
            walk(node["additionalProperties"], f"{path}[*]")
        if isinstance(node.get("items"), dict):
            walk(node["items"], f"{path}[]")

    schema = generate_crd_schema()
    walk(schema)
    # spec must cover the job surface a user writes.
    spec_props = schema["properties"]["spec"]["properties"]
    for key in ("replicaSpecs", "runPolicy", "successPolicy", "slice"):
        assert key in spec_props


def test_rbac_manifest_parses_and_covers_runtime_verbs():
    with open(os.path.join(REPO, "manifests", "base", "rbac.yaml")) as f:
        docs = list(yaml.safe_load_all(f))
    kinds = {d["kind"] for d in docs}
    assert kinds == {"ServiceAccount", "ClusterRole", "ClusterRoleBinding"}
    role = next(d for d in docs if d["kind"] == "ClusterRole")
    rules = {(g, r): set(rule["verbs"])
             for rule in role["rules"]
             for g in rule["apiGroups"] for r in rule["resources"]}
    # The verbs runtime/kube.py actually issues.
    assert {"create", "delete", "patch", "list",
            "watch"} <= rules[("", "pods")]
    assert {"get", "list", "watch",
            "patch"} <= rules[("tpu-operator.dev", "tpujobs")]
    assert "patch" in rules[("tpu-operator.dev", "tpujobs/status")]
    assert {"get", "create", "update"} <= rules[
        ("coordination.k8s.io", "leases")]
    # Recorder posts + aggregates; the SDK reads them back in e2e.
    assert {"create", "patch", "list"} <= rules[("", "events")]
    # SDK log reads go through the apiserver's kubelet-log proxy.
    assert "get" in rules[("", "pods/log")]
    # KubePdbControl.sync PATCHes minAvailable on gang-threshold change.
    assert {"create", "delete", "patch"} <= rules[
        ("policy", "poddisruptionbudgets")]
    # Slice-gang binder: node inventory reads + pods/binding writes.
    assert {"get", "list", "watch"} <= rules[("", "nodes")]
    assert "create" in rules[("", "pods/binding")]

def test_base_kustomization_lists_every_manifest():
    """`kubectl apply -k` of the overlays resolves ../../base — the
    base kustomization must exist and name exactly the deployable
    manifests that live there (a missing entry silently skips a
    resource; a stale one breaks the build)."""
    base = os.path.join(REPO, "manifests", "base")
    with open(os.path.join(base, "kustomization.yaml")) as f:
        kust = yaml.safe_load(f)
    listed = set(kust["resources"])
    on_disk = {p for p in os.listdir(base)
               if p.endswith(".yaml") and p != "kustomization.yaml"}
    assert listed == on_disk, (listed, on_disk)


@pytest.mark.parametrize("overlay", ("standalone", "kubeflow"))
def test_overlays_reference_base(overlay):
    path = os.path.join(REPO, "manifests", "overlays", overlay,
                        "kustomization.yaml")
    with open(path) as f:
        kust = yaml.safe_load(f)
    assert "../../base" in kust["resources"]
    # Every locally-referenced resource file exists.
    for res in kust["resources"]:
        if not res.startswith(".."):
            assert os.path.exists(os.path.join(os.path.dirname(path),
                                               res)), res


# CI shard (pyproject [tool.pytest.ini_options] markers)
pytestmark = pytest.mark.control_plane
