"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip TPU hardware is not available in CI; all sharding/collective
tests run on XLA's host platform with 8 virtual devices
(--xla_force_host_platform_device_count), per the multi-chip test strategy.
"""

import os
import sys

_FLAG = "--xla_force_host_platform_device_count=8"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " " + _FLAG).strip()

# Force the CPU platform before any backend initialization. The environment
# may pin JAX_PLATFORMS to a TPU plugin (axon); jax.config wins if applied
# before first device query.
os.environ["JAX_PLATFORMS"] = "cpu"
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except Exception:  # jax missing or already initialized — tests will surface it
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
