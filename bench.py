"""Benchmark: the BASELINE headline metric — ResNet-50 images/sec/chip.

Runs on whatever accelerator is available (one real TPU chip under the
driver; CPU fallback for smoke). Prints exactly ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline is measured MFU / 0.55 — the BASELINE.md target (the
reference publishes no numbers; ≥55% MFU ResNet-50 is the north star),
so vs_baseline >= 1.0 means the target is met.
"""

from __future__ import annotations

import json
import sys
import time

# Peak dense bf16 FLOP/s per chip (public Cloud TPU specs).
PEAK_FLOPS = {
    "v4": 275e12,
    "v5p": 459e12,
    "v5e": 197e12,
    "v5 lite": 197e12,
    "v6e": 918e12,
    "cpu": 1e11,  # nominal, for smoke runs only
}

# ResNet-50 @224: ~4.09 GFLOP forward per image; train step ~3x forward.
RESNET50_TRAIN_FLOPS_PER_IMAGE = 3 * 4.09e9

# Outlier-rep guard (round-5 verdict: BENCH_r05.json shipped a 238 img/s
# rep against a 2,610 best — a tunnel stall mid-rep — with spread_frac
# 0.91; one more bad rep would have flipped the median the whole
# round-over-round series rests on). When the rep spread exceeds the
# threshold, run up to MAX_EXTRA_REPS replacements and report the median
# of the stable set, recording every discarded rep + cause in the
# artifact.
SPREAD_THRESHOLD = 0.1
MAX_EXTRA_REPS = 2


def _spread_frac(values) -> float:
    s = sorted(values)
    median = s[len(s) // 2]
    return (s[-1] - s[0]) / median if median else 0.0


def _stablest_subset(times, k):
    """Indices of the k-member subset with the smallest spread — the
    'stable set'. n stays <= base+extra (5), so brute force is fine."""
    import itertools

    return min(itertools.combinations(range(len(times)), k),
               key=lambda idx: _spread_frac([times[i] for i in idx]))


def collect_reps(run_block, base_reps: int = 3,
                 spread_threshold: float = SPREAD_THRESHOLD,
                 max_extra: int = MAX_EXTRA_REPS):
    """Run ``run_block`` (-> seconds per timed block) ``base_reps``
    times; while no ``base_reps``-sized subset of the reps agrees
    within ``spread_threshold``, run one extra rep (up to
    ``max_extra``). Report the stablest subset — stalled reps (in
    either direction) are replaced instead of corrupting the reported
    median, and majority-stall rounds still converge once enough clean
    reps exist. Returns (kept_times, discarded) where ``discarded`` is
    [{"seconds", "cause"}, ...] for the artifact. The stable set keeps
    ``base_reps`` members, so the reported stat stays a median-of-3
    comparable round over round."""
    times = [run_block() for _ in range(base_reps)]
    for _ in range(max_extra):
        kept = _stablest_subset(times, base_reps)
        if _spread_frac([times[i] for i in kept]) <= spread_threshold:
            break
        times.append(run_block())
    kept = set(_stablest_subset(times, base_reps))
    discarded = [
        {"seconds": round(times[i], 6),
         "cause": f"spread_frac>{spread_threshold} (outlier rep; "
                  "host/tunnel stall suspected)"}
        for i in range(len(times)) if i not in kept]
    return [times[i] for i in sorted(kept)], discarded


def detect_chip() -> str:
    import jax

    d = jax.devices()[0]
    kind = getattr(d, "device_kind", "").lower()
    aliases = {"v5 lite": "v5e", "v6 lite": "v6e"}
    for name in ("v6e", "v6 lite", "v5p", "v5e", "v5 lite", "v4"):
        if name in kind:
            return aliases.get(name, name)
    return "cpu" if d.platform == "cpu" else "v5e"


def build_bench_step(batch_size: int, image_size: int,
                     stem: str = "conv7", steps_per_call: int = 1):
    """The exact benchmarked program: (step_fn, state, batch).

    Shared with benchmarks/profile_step.py so the profile is of this
    step, not a re-implementation that could drift.
    """
    import jax
    import jax.numpy as jnp
    import optax

    from tf_operator_tpu.models import resnet as rn
    from tf_operator_tpu.parallel.mesh import MeshConfig, make_mesh
    from tf_operator_tpu.parallel.sharding import CNN_RULES
    from tf_operator_tpu.train.trainer import Trainer, classification_loss

    mesh = make_mesh(MeshConfig(dp=-1), devices=jax.devices()[:1])
    cfg = rn.resnet50(stem=stem)
    trainer = Trainer(model=rn.ResNet(cfg), param_axes_fn=rn.param_logical_axes,
                      rules=CNN_RULES, mesh=mesh,
                      optimizer=optax.sgd(0.1, momentum=0.9),
                      loss_fn=classification_loss,
                      grad_norm_metric=False)
    rng = jax.random.PRNGKey(0)
    batch = rn.synthetic_batch(rng, batch_size=batch_size,
                               image_size=image_size)
    # Feed bf16 images: the standard TPU input pipeline emits bf16, and
    # it saves the per-step f32->bf16 cast of the image tensor.
    batch["inputs"] = batch["inputs"].astype(jnp.bfloat16)
    batch = {k: jnp.asarray(v) for k, v in batch.items()}
    state, shardings = trainer.init(rng, batch)
    return (trainer.make_train_step(shardings, batch,
                                    steps_per_call=steps_per_call),
            state, batch)


def bench_resnet50(batch_size: int, image_size: int, steps: int,
                   warmup: int, stem: str = "conv7",
                   steps_per_call: int = 1,
                   data_pipeline: bool = False):
    """``steps``/``warmup`` count optimizer steps; with
    ``steps_per_call > 1`` they are grouped into scan-fused dispatches
    (steps must divide evenly).

    ``data_pipeline=True`` (env TPU_BENCH_DATA_PIPELINE=1; ROADMAP item
    5, input-pipeline leg) feeds a FRESH host batch every step through
    the async double-buffered prefetch (train/data.prefetch_to_device)
    instead of the resident static batch — measuring the step as a real
    training loop feeds it. Forces steps_per_call=1 (a scan-fused
    dispatch consumes one resident batch by construction) and is a
    different config_fingerprint: the two modes are not comparable."""
    assert steps % steps_per_call == 0 and warmup % steps_per_call == 0
    step, state, batch = build_bench_step(batch_size, image_size,
                                          stem=stem,
                                          steps_per_call=steps_per_call)
    next_batch = lambda: batch
    if data_pipeline:
        assert steps_per_call == 1, "data_pipeline mode is per-step fed"
        import jax

        from tf_operator_tpu.train.data import (
            images_pipeline,
            prefetch_to_device,
        )

        dev = jax.sharding.SingleDeviceSharding(jax.devices()[0])
        fed = prefetch_to_device(
            images_pipeline(batch_size, image_size),
            {"inputs": dev, "labels": dev}, depth=2)
        next_batch = lambda: next(fed)  # noqa: E731

    for _ in range(warmup):
        state, metrics = step(state, next_batch())
    float(metrics["loss"])  # host sync (block_until_ready can return early
    # on plugin backends whose buffers report ready before execution)

    # Median of >=3 timed repetitions with reported spread: max-of-n
    # flatters one lucky run; the median is robust to one-off host or
    # tunnel hiccups in both directions and comparable round over round.
    # collect_reps replaces outlier reps (spread_frac > threshold) with
    # re-runs so one mid-rep stall cannot flip the median.
    state_box = [state]

    def run_block() -> float:
        t0 = time.perf_counter()
        for _ in range(steps):
            state_box[0], m = step(state_box[0], next_batch())
        float(m["loss"])
        return time.perf_counter() - t0

    times, discarded = collect_reps(run_block)
    state = state_box[0]
    rates = sorted(batch_size * steps / dt for dt in times)
    median = rates[len(rates) // 2]
    spread = (rates[-1] - rates[0]) / median if median else 0.0

    # Companion stat: the tunnel charges a fixed host-sync cost per
    # timed block (~90 ms measured; docs/benchmarks.md "Timing
    # methodology note"), so a single block's rate understates steady-
    # state training throughput. Extrapolate t(n) = t_step + C/n from
    # the median block and one 3x-longer block. The primary value stays
    # the round-1-comparable median; this reports what the chip
    # actually sustains.
    t_med = sorted(times)[len(times) // 2]
    t0 = time.perf_counter()
    for _ in range(3 * steps):
        state, metrics = step(state, next_batch())
    float(metrics["loss"])
    t_long = time.perf_counter() - t0
    per_step = (t_long - t_med) / (2 * steps)
    # Degenerate extrapolation (timer hiccup): report null, not a number
    # that masquerades as "sync cost exactly zero".
    corrected = batch_size / per_step if per_step > 0 else None
    return median, {"best": rates[-1], "worst": rates[0],
                    "spread_frac": round(spread, 4), "reps": len(rates),
                    "discarded_reps": discarded,
                    "sync_corrected": (round(corrected, 2)
                                       if corrected else None)}


def bench_environment(chip: str) -> dict:
    """Environment fingerprint for the artifact: jax version + platform
    facts, so round-over-round medians are auditable against
    environment drift (a jax upgrade or a different chip kind behind
    the tunnel must be visible in the JSON line, not archaeology)."""
    import platform as _plat

    import jax

    d = jax.devices()[0]
    return {
        "jax_version": jax.__version__,
        "platform": d.platform,
        "chip_kind": getattr(d, "device_kind", "") or chip,
        "python": _plat.python_version(),
    }


def bench_config_fingerprint(config: dict) -> str:
    """Stable digest of the measured configuration — two artifacts with
    the same fingerprint are comparable; a config drift (batch, stem,
    dispatch fusion, rep policy) changes it."""
    import hashlib

    return hashlib.sha1(
        json.dumps(config, sort_keys=True).encode()).hexdigest()[:12]


def main() -> int:
    try:
        import os as _os

        import jax

        chip = detect_chip()
        # Input-pipeline A/B (ROADMAP item 5): fresh prefetched batches
        # per step instead of the resident static batch. Opt-in and
        # fingerprint-changing — never silently alters the headline.
        data_pipeline = _os.environ.get("TPU_BENCH_DATA_PIPELINE") == "1"
        if chip == "cpu":
            # CPU smoke run is not the benchmark config: report the
            # throughput but claim zero baseline credit.
            config = {"batch_size": 8, "image_size": 64, "steps": 3,
                      "warmup": 1, "stem": "conv7", "steps_per_call": 1,
                      "spread_threshold": SPREAD_THRESHOLD,
                      "max_extra_reps": MAX_EXTRA_REPS}
            if data_pipeline:
                config["data_pipeline"] = True
            imgs_per_sec, stats = bench_resnet50(
                batch_size=8, image_size=64, steps=3, warmup=1,
                data_pipeline=data_pipeline)
            mfu = 0.0
        else:
            # Measured config (docs/benchmarks.md round-4 A/B table):
            # space-to-depth stem (exact 7x7 rewrite, MXU-shaped) and
            # 32-step scan-fused dispatch (amortizes the per-dispatch
            # host/tunnel cost the sync_corrected stat used to estimate
            # out). Batch 256/chip as in rounds 1-3.
            # NOT raised further (e.g. k=64 / 192-step blocks reads
            # 2 627): longer timed blocks only amortize the tunnel's
            # fixed per-block sync cost — a measurement artifact the
            # sync_corrected stat already isolates — and would break
            # the round-over-round comparability of the median.
            config = {"batch_size": 256, "image_size": 224, "steps": 96,
                      "warmup": 32, "stem": "s2d", "steps_per_call": 32,
                      "spread_threshold": SPREAD_THRESHOLD,
                      "max_extra_reps": MAX_EXTRA_REPS}
            if data_pipeline:
                # Per-step fed mode cannot scan-fuse (one batch per
                # dispatch); documented A/B config in docs/benchmarks.md.
                config.update({"steps_per_call": 1, "data_pipeline": True})
            imgs_per_sec, stats = bench_resnet50(
                batch_size=256, image_size=224, steps=96, warmup=32,
                stem="s2d",
                steps_per_call=1 if data_pipeline else 32,
                data_pipeline=data_pipeline)
            flops = imgs_per_sec * RESNET50_TRAIN_FLOPS_PER_IMAGE
            mfu = flops / PEAK_FLOPS[chip]
            if chip == "v5e":
                # Round-3 full-step profile (benchmarks/profile_step.py):
                # the step is HBM-bandwidth-bound; report the profiled
                # perfect-bandwidth floor so the headline can be read
                # against the measured hardware ceiling, not only the
                # 55%-MFU model-bound target. Derived from the profile
                # JSON so a re-profile updates it.
                try:
                    import os
                    prof = os.path.join(os.path.dirname(
                        os.path.abspath(__file__)), "benchmarks",
                        "results_profile_v5e.json")
                    with open(prof) as f:
                        summary = json.load(f)
                    floor_ms = summary["perfect_bw_floor_ms"]
                    # Only valid if the profile measured this config.
                    if summary.get("batch_size") == 256 and floor_ms > 0:
                        stats["platform_bw_ceiling_img_s"] = round(
                            256 / (floor_ms / 1000))
                except Exception:
                    pass  # optional companion stat; never fail the bench
        print(json.dumps({
            "metric": f"resnet50_images_per_sec_per_chip[{chip}]",
            "value": round(imgs_per_sec, 2),
            "unit": "images/sec/chip",
            "vs_baseline": round(mfu / 0.55, 4),
            "stat": "median_of_3",
            "spread": stats,
            "env": bench_environment(chip),
            "config_fingerprint": bench_config_fingerprint(config),
        }))
        return 0
    except Exception as e:  # one JSON line, even on failure
        out = {
            "metric": "resnet50_images_per_sec_per_chip",
            "value": 0.0,
            "unit": "images/sec/chip",
            "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}",
        }
        try:
            out["env"] = bench_environment("cpu")
        except Exception:
            pass  # jax itself broken; the error field carries the story
        print(json.dumps(out))
        return 1


if __name__ == "__main__":
    sys.exit(main())
