#!/usr/bin/env python3
"""Flag/backend parity drift check: cli.py kube gates vs the docs.

The CLI's feature flags (``--enable-*``) are either accepted on
``--backend kube`` or rejected by a ``parser.error`` gate in
``main()``. Both sides rot independently: a gate whose cited doc no
longer exists (or no longer explains the gate) strands the operator it
just rejected, and a doc still claiming a flag is rejected after the
gate was lifted sends users away from a working path. This checker
pins the contract — wired into tier-1 as tests/test_flag_parity.py:

- every kube gate message names the flag it rejects, cites at least
  one ``docs/*.md`` file, and that file exists and discusses the flag
  on kube;
- no doc paragraph claims a flag is rejected / not yet supported on
  kube unless the gate actually exists in cli.py;
- every serving front-door flag (``--gateway-*`` / ``--autoscale-*`` /
  ``--enable-serving-*``) is documented in docs/serving.md — the
  gateway and autoscaler are operated from that page, so an
  undocumented knob there is unreachable by its audience;
- every sharding flag (``--shards`` / ``--shard-index``) is documented
  in docs/robustness.md — the ``--shards`` kube gate sends rejected
  operators to that page's 'Sharded control plane' section.

Usage: python hack/verify-flag-parity.py   # exit 0 clean, 1 on drift
"""

from __future__ import annotations

import glob
import os
import re
import sys
from typing import Dict, List, Set, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLI = os.path.join(REPO, "tf_operator_tpu", "cli.py")
DOCS_DIR = os.path.join(REPO, "docs")

# parser.error("..." "..."): adjacent string literals only (the cli.py
# house style), so parentheses inside the message cannot truncate the
# match.
_ERROR_CALL = re.compile(r'parser\.error\(\s*((?:"(?:[^"\\]|\\.)*"\s*)+)\)')
_STR = re.compile(r'"((?:[^"\\]|\\.)*)"')
_FLAG_AT_START = re.compile(r"^(--enable-[a-z-]+|--shards)\b")
_DOC_CITE = re.compile(r"docs/([a-z0-9_-]+\.md)")
# Doc-side claims that a flag is unavailable on kube.
_REJECTION_WORDS = ("not yet supported", "rejects", "rejected")


def _parser_flags(prefixes: Tuple[str, ...]) -> Set[str]:
    sys.path.insert(0, REPO)
    from tf_operator_tpu.cli import build_parser

    flags: Set[str] = set()
    for action in build_parser()._actions:
        for opt in action.option_strings:
            if opt.startswith(prefixes):
                flags.add(opt)
    return flags


def enable_flags() -> Set[str]:
    """Every --enable-* flag the CLI parser accepts."""
    return _parser_flags(("--enable-",))


def serving_flags() -> Set[str]:
    """The serving front-door flag family (gateway + autoscaler): all
    must be documented in docs/serving.md."""
    return _parser_flags(("--gateway-", "--autoscale-",
                          "--enable-serving-"))


def sharding_flags() -> Set[str]:
    """The control-plane sharding flag family (--shards,
    --shard-index): all must be documented in docs/robustness.md."""
    return _parser_flags(("--shard",))


def kube_gates(path: str = CLI) -> Dict[str, Tuple[str, List[str]]]:
    """flag -> (gate message, cited docs files) for every parser.error
    gate that rejects an --enable-* flag on --backend kube."""
    with open(path, encoding="utf-8") as f:
        src = f.read()
    gates: Dict[str, Tuple[str, List[str]]] = {}
    for call in _ERROR_CALL.finditer(src):
        message = "".join(_STR.findall(call.group(1)))
        if "kube" not in message:
            continue
        flag = _FLAG_AT_START.match(message)
        if flag is None:
            continue  # backend/api-port plumbing errors, not flag gates
        gates[flag.group(1)] = (message, _DOC_CITE.findall(message))
    return gates


def _doc_paragraphs(path: str) -> List[str]:
    with open(path, encoding="utf-8") as f:
        return re.split(r"\n\s*\n", f.read())


def check(cli_path: str = CLI, docs_dir: str = DOCS_DIR) -> List[str]:
    """All drift findings, empty when cli.py and the docs agree."""
    problems: List[str] = []
    flags = enable_flags() | sharding_flags()
    gates = kube_gates(cli_path)

    for flag, (message, cited) in sorted(gates.items()):
        if flag not in flags:
            problems.append(
                f"{flag} is gated off --backend kube in cli.py main() but "
                "is not a flag build_parser() accepts (typo in the gate?)")
            continue
        if not cited:
            problems.append(
                f"{flag}'s kube gate cites no docs/*.md file — a rejected "
                "operator has nowhere to go")
            continue
        for doc in cited:
            doc_path = os.path.join(docs_dir, doc)
            if not os.path.exists(doc_path):
                problems.append(
                    f"{flag}'s kube gate cites docs/{doc}, which does not "
                    "exist")
                continue
            with open(doc_path, encoding="utf-8") as f:
                text = f.read()
            if flag not in text or "kube" not in text:
                problems.append(
                    f"docs/{doc} is cited by {flag}'s kube gate but does "
                    f"not discuss {flag} on the kube backend")

    # Docs claiming a rejection the CLI no longer performs.
    for doc_path in sorted(glob.glob(os.path.join(docs_dir, "*.md"))):
        doc = os.path.basename(doc_path)
        for para in _doc_paragraphs(doc_path):
            if "kube" not in para:
                continue
            lowered = para.lower()
            if not any(w in lowered for w in _REJECTION_WORDS):
                continue
            for flag in sorted(flags - set(gates)):
                # Boundary match: --enable-serving must not fire on a
                # paragraph that only names --enable-serving-autoscaler.
                if re.search(re.escape(flag) + r"(?![a-z-])", para):
                    problems.append(
                        f"docs/{doc} claims {flag} is rejected on the kube "
                        "backend, but cli.py has no such gate (lifted "
                        "without updating the doc?)")

    # Serving front-door flags must be operable from docs/serving.md.
    serving_doc = os.path.join(docs_dir, "serving.md")
    serving_text = ""
    if os.path.exists(serving_doc):
        with open(serving_doc, encoding="utf-8") as f:
            serving_text = f.read()
    for flag in sorted(serving_flags()):
        if flag not in serving_text:
            problems.append(
                f"{flag} is a serving front-door flag but docs/serving.md "
                "never mentions it — the gateway/autoscaler page is its "
                "only discoverable home")

    # Sharding flags must be operable from docs/robustness.md — the
    # --shards kube gate sends rejected operators there.
    robustness_doc = os.path.join(docs_dir, "robustness.md")
    robustness_text = ""
    if os.path.exists(robustness_doc):
        with open(robustness_doc, encoding="utf-8") as f:
            robustness_text = f.read()
    for flag in sorted(sharding_flags()):
        if flag not in robustness_text:
            problems.append(
                f"{flag} is a control-plane sharding flag but "
                "docs/robustness.md never mentions it — the 'Sharded "
                "control plane' section is its only discoverable home")
    return problems


def main() -> int:
    problems = check()
    for p in problems:
        print(f"DRIFT: {p}")
    if problems:
        print(f"{len(problems)} flag-parity drift problem(s)")
        return 1
    gates = kube_gates()
    print(f"ok: {len(enable_flags())} --enable-* flags, {len(gates)} kube "
          f"gate(s) ({', '.join(sorted(gates)) or 'none'}), cli and docs "
          "agree")
    return 0


if __name__ == "__main__":
    sys.exit(main())
