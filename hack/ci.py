#!/usr/bin/env python3
"""One-command CI: exactly what the hosted pipeline runs, runnable
locally (the reference encodes its matrix as Argo workflows + Prow,
test/workflows/components/workflows.libsonnet + prow_config.yaml;
.github/workflows/ci.yaml mirrors this file).

Stages, fail-fast in order:

  1. lint        hack/py_checks.py (compile, unused imports,
                 generated-files freshness — this stage alone would
                 have caught the round-3 broken-entrypoint regression
                 once paired with the control_plane shard)
  2. control_plane  pytest -m control_plane   (fast operator signal)
  3. compute        pytest -m compute         (model/kernel compiles)
  4. e2e            pytest -m e2e             (subprocess pod suites)
  5. bench-smoke    bench.py on whatever accelerator exists (CPU ok):
                 asserts the benchmark ENTRYPOINT works and emits its
                 one-line JSON contract, not a performance level.

Usage:
  python hack/ci.py               # everything
  python hack/ci.py --stages lint,control_plane
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

STAGES = ("lint", "control_plane", "compute", "e2e", "bench-smoke")

SHARD_MARKS = ("control_plane", "compute", "e2e")


def _check_marker_totality() -> int:
    """Every test must carry a shard marker, or the shard matrix
    silently skips it forever (each job deselects it, all stay green).
    Enforced in lint so the failure names the unmarked tests."""
    expr = " and ".join(f"not {m}" for m in SHARD_MARKS)
    proc = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/", "--collect-only",
         "-q", "-m", expr, "--color=no"],
        cwd=REPO, capture_output=True, text=True)
    lines = [ln for ln in proc.stdout.splitlines()
             if "::" in ln and not ln.startswith("=")]
    if lines:
        print("ci: [lint] tests with NO shard marker (would never run "
              "in any CI shard):")
        for ln in lines:
            print(f"ci: [lint]   {ln}")
        return 1
    return 0


def run(stage: str) -> int:
    env = dict(os.environ)
    if stage == "lint":
        rc = _check_marker_totality()
        if rc != 0:
            return rc
        cmd = [sys.executable, "hack/py_checks.py"]
    elif stage in ("control_plane", "compute", "e2e"):
        cmd = [sys.executable, "-m", "pytest", "tests/", "-q",
               "-m", stage, "--color=no"]
    elif stage == "bench-smoke":
        cmd = [sys.executable, "bench.py"]
        # Smoke contract: run wherever CI runs (usually CPU).
        env.setdefault("JAX_PLATFORMS", "cpu")
    else:
        raise ValueError(stage)
    t0 = time.monotonic()
    print(f"ci: [{stage}] {' '.join(cmd)}", flush=True)
    proc = subprocess.run(cmd, cwd=REPO, env=env,
                          capture_output=(stage == "bench-smoke"),
                          text=True)
    if stage == "bench-smoke" and proc.returncode == 0:
        # The contract: the LAST stdout line is one JSON object with
        # the metric fields the driver records.
        try:
            line = proc.stdout.strip().splitlines()[-1]
            rec = json.loads(line)
            assert {"metric", "value", "unit",
                    "vs_baseline"} <= set(rec), rec
            print(f"ci: [bench-smoke] {line}")
        except Exception as e:
            print(f"ci: [bench-smoke] BAD OUTPUT CONTRACT: {e}\n"
                  f"{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}")
            return 1
    elif stage == "bench-smoke":
        print(proc.stdout[-2000:])
        print(proc.stderr[-2000:])
    dt = time.monotonic() - t0
    print(f"ci: [{stage}] {'ok' if proc.returncode == 0 else 'FAILED'} "
          f"in {dt:.0f}s", flush=True)
    return proc.returncode


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--stages", default=",".join(STAGES),
                    help=f"comma list from: {', '.join(STAGES)}")
    args = ap.parse_args()
    stages = [s.strip() for s in args.stages.split(",") if s.strip()]
    for s in stages:
        if s not in STAGES:
            ap.error(f"unknown stage {s!r}")
    results = {}
    for stage in stages:
        rc = run(stage)
        results[stage] = rc
        if rc != 0:
            break  # fail fast; later stages would drown the signal
    print("ci summary:", json.dumps(
        {s: ("ok" if rc == 0 else "FAILED") for s, rc in results.items()}))
    return 0 if all(rc == 0 for rc in results.values()) else 1


if __name__ == "__main__":
    sys.exit(main())
