#!/usr/bin/env bash
# Regenerate all checked-in generated artifacts (reference:
# hack/update-codegen.sh + hack/generate-apidoc.sh). The freshness check
# (verify-codegen.sh analog) is tests/test_manifests.py and
# hack/py_checks.py.
set -euo pipefail
cd "$(dirname "$0")/.."
python manifests/gen.py
python docs/gen_api.py
echo "update-codegen: done"
