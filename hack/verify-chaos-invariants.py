#!/usr/bin/env python
"""Randomized chaos property check for the whole control plane
(controller + gang admission + checkpoint barriers, runtime/chaos.py
fault injection).

Each round draws a random fleet shape (jobs x workers), a random
``FaultProfile`` (write/read 5xx, 409 conflicts, timeouts, stale reads,
dropped watch events — every class at a non-trivial rate) and a random
number of planned disruptions, then runs REAL reconciliation through
the fault-injecting store with one operator crash-restart mid-run, and
asserts the post-convergence invariants:

1. **Convergence**: every job reaches Succeeded despite the faults —
   level-triggered reconcile + the shared retry layer
   (runtime/retry.py) must absorb any profile, given time.
2. **No orphaned pods**: every pod's controller owner exists; no two
   live pods share a (job, replica-type, index) identity (a lost
   expectation would double-create).
3. **No duplicate gang admissions**: concurrently admitted chips never
   exceed the budget at any sampled instant (sampled at 20 Hz while
   the run churns).
4. **Every opened checkpoint barrier resolves**: acked or timeout —
   displacements only execute after a barrier outcome, and none is
   left in flight at convergence.
5. **Restart-with-identity never loses committed steps**: no recreated
   worker restores from below the committed-step watermark recorded at
   its eviction.
6. **Elastic invariants** (rounds with the resize pass on): a gang is
   never resized below its ``minSlices`` floor, admitted chips stay
   within the budget at each group's CURRENT size mid-resize, and
   every shrink's save-before-evict barrier resolves acked|timeout.

The harness is ``benchmarks/bench_controlplane.py run_chaos_bench`` —
the same machinery the ``--chaos`` scenario pins at the 200x16 shape —
so the fuzz and the benchmark can never drift apart.

``--sharded`` switches to the split-brain rounds: two operator
replicas contend for N shard leases (jobs hashed by (namespace, uid)),
reconcile through the same fault classes, and mid-run a shard holder
is killed WITHOUT releasing its lease; the survivor must take over
after expiry with every sync on the owning shard, never two live
controllers per shard, and no orphaned/duplicate pods.

Usage:
    python hack/verify-chaos-invariants.py                 # 10 rounds
    python hack/verify-chaos-invariants.py --rounds 3 --seed 7
    python hack/verify-chaos-invariants.py --sharded --rounds 3

Exit status 0 = all rounds clean; 1 = a violation, with the repro seed
on stderr. Wired into tier-1 as tests/test_chaos_invariants.py (smoke
round count, pinned seed list including every regression seed found
during development).
"""

from __future__ import annotations

import argparse
import os
import random
import sys
from typing import List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "benchmarks"))

import bench_controlplane  # noqa: E402

from tf_operator_tpu.runtime.chaos import FaultProfile  # noqa: E402


def random_profile(rng: random.Random, seed: int) -> FaultProfile:
    """Every fault class at a non-trivial random rate — mean enough to
    exercise each retry/recovery path, bounded so convergence stays
    reachable inside a CI-sized timeout."""
    return FaultProfile(
        seed=seed,
        write_error_rate=rng.uniform(0.02, 0.10),
        conflict_rate=rng.uniform(0.02, 0.10),
        read_error_rate=rng.uniform(0.01, 0.05),
        timeout_rate=rng.uniform(0.01, 0.04),
        stale_read_rate=rng.uniform(0.02, 0.08),
        watch_drop_rate=rng.uniform(0.02, 0.08),
        lost_response_rate=rng.uniform(0.0, 0.02),
    )


def run_round(seed: int, timeout: float = 120.0,
              verbose: bool = False,
              elastic: Optional[bool] = None) -> List[str]:
    """One randomized round; returns invariant violations ([] = clean).
    A convergence timeout IS a violation — under any profile the fleet
    must converge, that is the level-triggered contract.

    ``elastic`` turns the resize pass on for the round (minSlices/
    maxSlices gangs, the grow pass plus a barrier-gated shrink
    exerciser, and the three elastic invariants: never below
    minSlices, budget held at each group's current size mid-resize,
    every shrink barrier resolved). None = drawn from the seed —
    drawn LAST so the fleet shape and fault profile of historical
    seeds stay byte-identical."""
    rng = random.Random(seed)
    jobs = rng.randint(3, 6)
    workers = rng.randint(2, 3)
    disruptions = rng.randint(1, 2)
    profile = random_profile(rng, seed)
    threadiness = rng.choice((2, 4))
    if elastic is None:
        elastic = rng.random() < 0.5
    try:
        result = bench_controlplane.run_chaos_bench(
            jobs=jobs, workers=workers, threadiness=threadiness,
            timeout=timeout, seed=seed, profile=profile,
            disruptions=disruptions, steps=30, save_interval=8,
            barrier_timeout=8.0, crash_restarts=1,
            resync_period=0.25, elastic=elastic)
    except TimeoutError as e:
        return [f"no convergence under profile seed {seed} "
                f"(elastic={elastic}): {e}"]
    if verbose:
        print(f"  seed {seed}: {jobs}x{workers} d{disruptions} "
              f"elastic={elastic} "
              f"faults={result['faults_injected_total']} "
              f"retries={result['retries_total']} "
              f"shrinks={result['shrinks_landed']} "
              f"converged {result['convergence_seconds']}s",
              file=sys.stderr)
    return list(result["invariant_violations"])


def run_shard_round(seed: int, timeout: float = 120.0,
                    verbose: bool = False) -> List[str]:
    """One randomized SHARDED round (--sharded): two operator replicas
    contend for a drawn number of shard leases, reconcile a drawn fleet
    through a drawn fault profile, and mid-run a shard holder is killed
    without releasing its lease — the split-brain window. Violations
    returned ([] = clean):

      * a job synced by a controller whose shard doesn't own its
        (namespace, uid) hash, or two live controllers on one shard
        (double-reconcile);
      * a crashed shard never re-acquired by the survivor;
      * orphaned pods / duplicate live pod identities;
      * no convergence inside the budget.

    A NEW draw stream (separate function, not a run_round flag) so the
    historical run_round seeds stay byte-identical."""
    rng = random.Random(seed)
    jobs = rng.randint(4, 8)
    workers = rng.randint(2, 3)
    shards = rng.choice((2, 3, 4))
    crashes = rng.randint(1, 2)
    profile = random_profile(rng, seed)
    threadiness = rng.choice((2, 4))
    try:
        result = bench_controlplane.run_sharded_chaos_bench(
            jobs=jobs, workers=workers, shards=shards,
            threadiness=threadiness, timeout=timeout, seed=seed,
            profile=profile, crashes=crashes, resync_period=0.25)
    except TimeoutError as e:
        return [f"no convergence under profile seed {seed} "
                f"(sharded): {e}"]
    if verbose:
        print(f"  seed {seed}: {jobs}x{workers} s{shards} "
              f"crashes={len(result['shard_crashes'])} "
              f"faults={result['faults_injected_total']} "
              f"failovers={result['failover_seconds']} "
              f"converged {result['convergence_seconds']}s",
              file=sys.stderr)
    return (list(result["ownership_violations"])
            + list(result["invariant_violations"]))


def run_rl_round(seed: int, timeout: float = 120.0,
                 verbose: bool = False) -> List[str]:
    """One randomized HETEROGENEOUS-GANG round (--rl): every job
    carries an explicit evict-class CPU-only actor pool next to its
    barrier-class learners, reconciles through the drawn fault profile
    with one operator crash-restart, and the disruptor is an actor
    KILL STORM (at least half of a job's pool deleted per round, no
    barrier, no displacement). Violations returned ([] = clean):

      * a learner (world-member) pod's uid changed while its job ran —
        actor-only churn restarted the learner world;
      * a job's committed step regressed under the storm;
      * orphaned pods / duplicate live pod identities / capacity
        breaches / no convergence (the base invariants).

    A NEW draw stream (separate function, not a run_round flag) so the
    historical run_round seeds stay byte-identical."""
    rng = random.Random(seed)
    jobs = rng.randint(2, 4)
    workers = rng.randint(2, 3)
    actors = rng.randint(2, 4)
    storms = rng.randint(1, 2)
    profile = random_profile(rng, seed)
    threadiness = rng.choice((2, 4))
    try:
        result = bench_controlplane.run_chaos_bench(
            jobs=jobs, workers=workers, threadiness=threadiness,
            timeout=timeout, seed=seed, profile=profile,
            disruptions=storms, steps=30, save_interval=8,
            barrier_timeout=8.0, crash_restarts=1,
            resync_period=0.25, elastic=False, rl=True, actors=actors)
    except TimeoutError as e:
        return [f"no convergence under profile seed {seed} (rl): {e}"]
    if verbose:
        print(f"  seed {seed}: {jobs}x{workers}+{actors}a "
              f"storms={result['actor_kill_storms']} "
              f"kills={result['actor_kills']} "
              f"faults={result['faults_injected_total']} "
              f"retries={result['retries_total']} "
              f"converged {result['convergence_seconds']}s",
              file=sys.stderr)
    return list(result["invariant_violations"])


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    p.add_argument("--rounds", type=int, default=10)
    p.add_argument("--seed", type=int, default=None,
                   help="base seed (default: random; printed for repro)")
    p.add_argument("--timeout", type=float, default=120.0,
                   help="per-round convergence budget in seconds")
    p.add_argument("--sharded", action="store_true",
                   help="run the sharded split-brain rounds (N shard "
                        "leases, two replicas, mid-run leader kill) "
                        "instead of the single-operator rounds")
    p.add_argument("--rl", action="store_true",
                   help="run the heterogeneous-gang rounds (explicit "
                        "evict-class actor pools beside barrier-class "
                        "learners, actor kill storms as the "
                        "disruptor) instead of the single-operator "
                        "rounds; checks the learner-incarnation and "
                        "committed-step invariants (docs/rl.md)")
    p.add_argument("--verbose", action="store_true")
    args = p.parse_args(argv)
    base = args.seed if args.seed is not None else \
        random.SystemRandom().randint(0, 2**31)
    if args.sharded:
        round_fn, mode = run_shard_round, "sharded "
    elif args.rl:
        round_fn, mode = run_rl_round, "rl "
    else:
        round_fn, mode = run_round, ""
    print(f"verify-chaos-invariants: {args.rounds} {mode}rounds, "
          f"base seed {base}", file=sys.stderr)
    for i in range(args.rounds):
        seed = base + i
        errors = round_fn(seed, timeout=args.timeout,
                          verbose=args.verbose)
        if errors:
            repro_flag = (" --sharded" if args.sharded
                          else " --rl" if args.rl else "")
            print(f"FAIL (repro: --seed {seed} --rounds 1{repro_flag}):",
                  file=sys.stderr)
            for e in errors:
                print(f"  {e}", file=sys.stderr)
            return 1
    if args.sharded:
        print("OK: converged under every fault profile; every sync on "
              "the owning shard, no double-reconcile, every crashed "
              "shard re-acquired, no orphans", file=sys.stderr)
    elif args.rl:
        print("OK: converged under every fault profile; actor kill "
              "storms never restarted a learner or regressed a "
              "committed step, no orphans, no duplicate admissions",
              file=sys.stderr)
    else:
        print("OK: converged under every fault profile; no orphans, no "
              "duplicate admissions, every barrier resolved, no "
              "committed steps lost, elastic floors/budget held",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
