#!/usr/bin/env python3
"""Metric-catalog drift check: runtime/metrics.py vs docs/monitoring.md.

Every metric registered in the code must appear in the docs catalog
with the right type, and every documented metric must still exist in
the code — wired into tier-1 as tests/test_metrics_docs.py so the
catalog cannot rot (an undocumented metric is invisible to operators;
a documented-but-deleted one sends them hunting for a series that will
never appear).

Usage: python hack/verify-metrics-docs.py   # exit 0 clean, 1 on drift
"""

from __future__ import annotations

import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOC = os.path.join(REPO, "docs", "monitoring.md")

# | `tpu_operator_foo_total{label}` | counter | meaning... |
_ROW = re.compile(
    r"^\|\s*`(tpu_operator_[a-z0-9_]+)(?:\{[^}]*\})?`\s*\|\s*(\w+)\s*\|")


def registered_metrics() -> dict:
    """name -> type from the live registry (importing the module IS the
    registration)."""
    sys.path.insert(0, REPO)
    from tf_operator_tpu.runtime.metrics import REGISTRY

    with REGISTRY._lock:
        return {name: m.kind for name, m in REGISTRY._metrics.items()}


def documented_metrics(path: str = DOC) -> dict:
    """name -> type from the docs/monitoring.md catalog tables."""
    out: dict = {}
    with open(path, encoding="utf-8") as f:
        for line in f:
            m = _ROW.match(line.strip())
            if m:
                out[m.group(1)] = m.group(2).lower()
    return out


def check() -> list:
    """All drift findings, empty when code and docs agree."""
    code = registered_metrics()
    docs = documented_metrics()
    problems = []
    for name in sorted(set(code) - set(docs)):
        problems.append(
            f"{name} ({code[name]}) is registered in runtime/metrics.py "
            "but missing from the docs/monitoring.md catalog")
    for name in sorted(set(docs) - set(code)):
        problems.append(
            f"{name} is documented in docs/monitoring.md but no longer "
            "registered in runtime/metrics.py")
    for name in sorted(set(code) & set(docs)):
        if code[name] != docs[name]:
            problems.append(
                f"{name}: registered as {code[name]} but documented as "
                f"{docs[name]}")
    return problems


def main() -> int:
    problems = check()
    for p in problems:
        print(f"DRIFT: {p}")
    if problems:
        print(f"{len(problems)} metric-catalog drift problem(s)")
        return 1
    print(f"ok: {len(registered_metrics())} metrics, code and docs agree")
    return 0


if __name__ == "__main__":
    sys.exit(main())
