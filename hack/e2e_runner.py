"""E2E test runner with retries and junit output.

Reference analog: py/kubeflow/tf_operator/test_runner.py:23-60 — the
Prow-facing harness that runs each e2e suite with retries/trials and
emits junit XML for the results dashboard. Here the suites are the
hermetic pytest e2e files (tests/test_e2e_local.py runs the real
controller + subprocess data plane), so the runner wraps pytest:
flaky-looking failures (infra timeouts) are retried per suite, and a
combined junit file is written for CI ingestion.

Usage:
    python hack/e2e_runner.py [--retries N] [--junit-dir DIR] [suite ...]
Suites default to the e2e + engine + bootstrap surfaces.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time
import xml.etree.ElementTree as ET

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DEFAULT_SUITES = [
    "tests/test_e2e_local.py",
    "tests/test_e2e_remote.py",
    "tests/test_kube.py",
    "tests/test_claim_races.py",
    "tests/test_engine.py",
    "tests/test_bootstrap.py",
    "tests/test_gang_admission.py",
    "tests/test_ps.py",
    # Round 5: binder placement + served-plane auth/TLS units.
    "tests/test_binder.py",
    "tests/test_apiserver.py",
    # Round 6: slice-health & auto-repair (maintenance-aware node
    # lifecycle with gang drain/rebind).
    "tests/test_health.py",
]


def run_suite(suite: str, junit_path: str, retries: int) -> bool:
    for attempt in range(retries + 1):
        cmd = [sys.executable, "-m", "pytest", suite, "-q",
               f"--junitxml={junit_path}"]
        print(f"[e2e-runner] {suite} (attempt {attempt + 1})", flush=True)
        proc = subprocess.run(cmd, cwd=REPO_ROOT)
        if proc.returncode == 0:
            return True
        print(f"[e2e-runner] {suite} failed (rc={proc.returncode})",
              flush=True)
    return False


def merge_junit(paths: list, out_path: str) -> None:
    suites = ET.Element("testsuites")
    for p in paths:
        if not os.path.exists(p):
            continue
        root = ET.parse(p).getroot()
        for el in (root.iter("testsuite") if root.tag == "testsuites"
                   else [root]):
            suites.append(el)
    ET.ElementTree(suites).write(out_path, encoding="unicode",
                                 xml_declaration=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("suites", nargs="*", default=None)
    ap.add_argument("--retries", type=int, default=1,
                    help="re-runs per failing suite before declaring failure")
    ap.add_argument("--junit-dir", default="/tmp/tpu-operator-junit")
    args = ap.parse_args(argv)
    suites = args.suites or DEFAULT_SUITES

    os.makedirs(args.junit_dir, exist_ok=True)
    t0 = time.monotonic()
    results, junit_files = {}, []
    for suite in suites:
        slug = suite.replace("/", "_").replace(".py", "")
        junit = os.path.join(args.junit_dir, f"junit_{slug}.xml")
        junit_files.append(junit)
        results[suite] = run_suite(suite, junit, args.retries)
    merged = os.path.join(args.junit_dir, "junit_e2e.xml")
    merge_junit(junit_files, merged)

    dt = time.monotonic() - t0
    failed = [s for s, ok in results.items() if not ok]
    for suite, ok in results.items():
        print(f"[e2e-runner] {'PASS' if ok else 'FAIL'} {suite}")
    print(f"[e2e-runner] {len(results) - len(failed)}/{len(results)} suites "
          f"passed in {dt:.0f}s; junit: {merged}")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
