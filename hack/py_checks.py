#!/usr/bin/env python3
"""Static checks for CI (reference: py/kubeflow/tf_operator/py_checks.py).

Runs, in order:
  1. byte-compilation of every tracked .py file (syntax gate);
  2. pyflakes when available (skipped with a notice otherwise — no
     network installs in the build image);
  3. the generated-artifact freshness checks (manifests/docs codegen),
     the verify-codegen.sh analog.

Exit code is non-zero on any failure so CI can gate merges on it.
"""

from __future__ import annotations

import compileall
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECK_DIRS = ["tf_operator_tpu", "tests", "examples", "hack", "manifests",
              "docs"]


def check_compile() -> bool:
    ok = True
    for d in CHECK_DIRS:
        path = os.path.join(ROOT, d)
        if os.path.isdir(path):
            ok = compileall.compile_dir(path, quiet=1, force=True) and ok
    for f in ("bench.py", "__graft_entry__.py"):
        ok = compileall.compile_file(os.path.join(ROOT, f), quiet=1) and ok
    return bool(ok)


def check_pyflakes() -> bool:
    try:
        import pyflakes  # noqa: F401
    except ImportError:
        print("py_checks: pyflakes not installed, skipping lint pass")
        return True
    targets = [os.path.join(ROOT, d) for d in CHECK_DIRS
               if os.path.isdir(os.path.join(ROOT, d))]
    proc = subprocess.run([sys.executable, "-m", "pyflakes", *targets])
    return proc.returncode == 0


GENERATED = [
    ("manifests/gen.py", "manifests/base/tpujob.schema.json"),
    ("docs/gen_api.py", "docs/api.md"),
]


def check_generated_fresh() -> bool:
    """Re-run each generator and diff its output against the checked-in
    artifact, restoring the original afterwards (verify-codegen.sh
    analog)."""
    ok = True
    for gen, artifact in GENERATED:
        path = os.path.join(ROOT, artifact)
        with open(path, "rb") as f:
            before = f.read()
        try:
            proc = subprocess.run([sys.executable, os.path.join(ROOT, gen)],
                                  capture_output=True, text=True)
            if proc.returncode != 0:
                print(f"py_checks: {gen} failed:\n{proc.stderr}")
                ok = False
                continue
            with open(path, "rb") as f:
                after = f.read()
            if after != before:
                print(f"py_checks: {artifact} is stale — run "
                      "hack/update-codegen.sh and commit the result")
                ok = False
        finally:
            with open(path, "wb") as f:
                f.write(before)
    return ok


def main() -> int:
    checks = [("compile", check_compile), ("pyflakes", check_pyflakes),
              ("generated-fresh", check_generated_fresh)]
    failed = []
    for name, fn in checks:
        print(f"py_checks: running {name}")
        if not fn():
            failed.append(name)
    if failed:
        print(f"py_checks: FAILED: {', '.join(failed)}")
        return 1
    print("py_checks: all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
