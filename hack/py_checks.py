#!/usr/bin/env python3
"""Static checks for CI (reference: py/kubeflow/tf_operator/py_checks.py).

Runs, in order:
  1. byte-compilation of every tracked .py file (syntax gate);
  2. pyflakes when available (skipped with a notice otherwise — no
     network installs in the build image);
  3. the generated-artifact freshness checks (manifests/docs codegen),
     the verify-codegen.sh analog.

Exit code is non-zero on any failure so CI can gate merges on it.
"""

from __future__ import annotations

import compileall
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECK_DIRS = ["tf_operator_tpu", "tests", "examples", "hack", "manifests",
              "docs"]


def check_compile() -> bool:
    ok = True
    for d in CHECK_DIRS:
        path = os.path.join(ROOT, d)
        if os.path.isdir(path):
            ok = compileall.compile_dir(path, quiet=1, force=True) and ok
    for f in ("bench.py", "__graft_entry__.py"):
        ok = compileall.compile_file(os.path.join(ROOT, f), quiet=1) and ok
    return bool(ok)


def check_pyflakes() -> bool:
    try:
        import pyflakes  # noqa: F401
    except ImportError:
        print("py_checks: pyflakes not installed, using builtin "
              "unused-import check")
        return check_unused_imports()
    targets = [os.path.join(ROOT, d) for d in CHECK_DIRS
               if os.path.isdir(os.path.join(ROOT, d))]
    proc = subprocess.run([sys.executable, "-m", "pyflakes", *targets])
    return proc.returncode == 0


def check_unused_imports() -> bool:
    """Minimal F401 analog: flag imports whose bound name never appears
    again in the module source. Conservative — `import a.b` binds `a`,
    star imports and `# noqa` lines are skipped."""
    import ast
    import io
    import tokenize

    ok = True
    for d in CHECK_DIRS:
        base = os.path.join(ROOT, d)
        if not os.path.isdir(base):
            continue
        for dirpath, _, files in os.walk(base):
            if "__pycache__" in dirpath:
                continue
            for fn in sorted(files):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                with open(path, "r") as f:
                    src = f.read()
                try:
                    tree = ast.parse(src)
                except SyntaxError:
                    continue  # check_compile reports it
                noqa_lines = set()
                for tok in tokenize.generate_tokens(
                        io.StringIO(src).readline):
                    if tok.type == tokenize.COMMENT and "noqa" in tok.string:
                        noqa_lines.add(tok.start[0])
                names = {}
                for node in ast.walk(tree):
                    if isinstance(node, ast.Import):
                        for a in node.names:
                            bound = (a.asname
                                     or a.name.split(".")[0])
                            names[bound] = (node.lineno,
                                            node.end_lineno or node.lineno)
                    elif isinstance(node, ast.ImportFrom):
                        for a in node.names:
                            if a.name == "*":
                                continue
                            names[a.asname or a.name] = (
                                node.lineno, node.end_lineno or node.lineno)
                # Attribute accesses hang off a Name node, so collecting
                # Names alone covers x.y usages too.
                used = {node.id for node in ast.walk(tree)
                        if isinstance(node, ast.Name)}
                for name, (lineno, end) in sorted(names.items(),
                                                  key=lambda kv: kv[1]):
                    if name in used or noqa_lines.intersection(
                            range(lineno, end + 1)):
                        continue
                    if name == "annotations":  # from __future__
                        continue
                    rel = os.path.relpath(path, ROOT)
                    print(f"py_checks: unused import '{name}' "
                          f"at {rel}:{lineno}")
                    ok = False
    return ok


GENERATED = [
    ("manifests/gen.py", "manifests/base/tpujob.schema.json"),
    ("docs/gen_api.py", "docs/api.md"),
]


def check_generated_fresh() -> bool:
    """Re-run each generator and diff its output against the checked-in
    artifact, restoring the original afterwards (verify-codegen.sh
    analog)."""
    ok = True
    for gen, artifact in GENERATED:
        path = os.path.join(ROOT, artifact)
        with open(path, "rb") as f:
            before = f.read()
        try:
            proc = subprocess.run([sys.executable, os.path.join(ROOT, gen)],
                                  capture_output=True, text=True)
            if proc.returncode != 0:
                print(f"py_checks: {gen} failed:\n{proc.stderr}")
                ok = False
                continue
            with open(path, "rb") as f:
                after = f.read()
            if after != before:
                print(f"py_checks: {artifact} is stale — run "
                      "hack/update-codegen.sh and commit the result")
                ok = False
        finally:
            with open(path, "wb") as f:
                f.write(before)
    return ok


def main() -> int:
    checks = [("compile", check_compile), ("pyflakes", check_pyflakes),
              ("generated-fresh", check_generated_fresh)]
    failed = []
    for name, fn in checks:
        print(f"py_checks: running {name}")
        if not fn():
            failed.append(name)
    if failed:
        print(f"py_checks: FAILED: {', '.join(failed)}")
        return 1
    print("py_checks: all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
