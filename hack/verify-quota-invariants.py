#!/usr/bin/env python
"""Randomized property check for the tenant-queue quota subsystem
(controller/quota.py + controller/gang.py).

Generates random cohort/queue topologies and random gang arrival/
completion schedules, runs real admission passes against an in-memory
Store, and asserts the subsystem's core invariants after every step:

1. **No admission above cohort capacity** — the chips held by admitted
   (Inqueue/Running) groups of a cohort's queues never exceed the
   cohort's aggregate nominal quota, borrowing included.
2. **No queue starves** — every generated group is sized to be
   admissible through its queue (need <= the queue's ceiling), so with
   completions freeing capacity, every group must eventually admit
   within a bounded number of drain rounds.
3. **Nominal floor under reclaim** — a reclaim never displaces a queue
   below its nominal occupancy unless the displaced gang itself
   straddles the boundary (gangs are indivisible; checked as: after
   any pass, a queue's admitted chips below nominal implies it has no
   borrowed peer still admitted in its cohort while it has pending
   nominal demand... folded into invariant 2's convergence).

Usage:
    python hack/verify-quota-invariants.py                # 50 rounds
    python hack/verify-quota-invariants.py --rounds 10 --seed 7

Exit status 0 = all rounds clean; 1 = a violation, with the repro seed
on stderr. Wired into tier-1 as tests/test_quota_invariants.py (small
round count, fixed seed).
"""

from __future__ import annotations

import argparse
import os
import random
import sys
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tf_operator_tpu.api.types import (  # noqa: E402
    ClusterQueue,
    ClusterQueueSpec,
    ReclaimPolicy,
    SliceGroup,
    SliceGroupSpec,
    TenantQueue,
    TenantQueueSpec,
    TPUSliceSpec,
)
from tf_operator_tpu.controller.gang import (  # noqa: E402
    PHASE_INQUEUE,
    PHASE_PENDING,
    PHASE_RUNNING,
    SliceGangScheduler,
)
from tf_operator_tpu.controller.quota import TenantQueueManager  # noqa: E402
from tf_operator_tpu.runtime import store as store_mod  # noqa: E402
from tf_operator_tpu.runtime.store import Store  # noqa: E402


class Topology:
    def __init__(self, rng: random.Random):
        self.rng = rng
        self.store = Store()
        self.mgr = TenantQueueManager(self.store)
        # queue name -> (cohort, nominal, ceiling)
        self.queues: Dict[str, tuple] = {}
        self.cohort_nominal: Dict[str, int] = {}
        n_cohorts = rng.randint(1, 2)
        qi = 0
        for ci in range(n_cohorts):
            cohort = f"cohort-{ci}"
            for _ in range(rng.randint(2, 4)):
                name = f"q{qi}"
                qi += 1
                nominal = rng.choice([4, 8, 16, 32])
                bl = rng.choice([None, None, 0, 4, 8])
                policy = rng.choice([ReclaimPolicy.ANY, ReclaimPolicy.ANY,
                                     ReclaimPolicy.LOWER_PRIORITY])
                cq = ClusterQueue(spec=ClusterQueueSpec(
                    nominal_chips=nominal, borrowing_limit=bl,
                    cohort=cohort, reclaim_policy=policy))
                cq.metadata.name = f"cq-{name}"
                cq.metadata.namespace = ""
                self.store.create(store_mod.CLUSTERQUEUES, cq)
                tq = TenantQueue(spec=TenantQueueSpec(
                    cluster_queue=f"cq-{name}"))
                tq.metadata.name = name
                self.store.create(store_mod.TENANTQUEUES, tq)
                self.queues[name] = (cohort, nominal, bl)
                self.cohort_nominal[cohort] = \
                    self.cohort_nominal.get(cohort, 0) + nominal
        # Physical capacity >= every cohort's nominal so quota is the
        # binding constraint the invariants exercise.
        total = sum(self.cohort_nominal.values())
        self.sched = SliceGangScheduler(
            self.store, total_chips=total, quota=self.mgr,
            fairness=rng.choice(["aged", "strict", "backfill"]),
            priority_classes={"hi": 100, "lo": 10})
        self._gi = 0

    def ceiling(self, qname: str) -> int:
        cohort, nominal, bl = self.queues[qname]
        cap = self.cohort_nominal[cohort]
        return min(nominal + bl, cap) if bl is not None else cap

    def add_group(self, qname: str) -> Optional[str]:
        ceiling = self.ceiling(qname)
        sizes = [c for c in (4, 8, 16, 32) if c <= ceiling]
        if not sizes:
            return None  # zero-ceiling queue: nothing admissible
        name = f"g{self._gi}"
        self._gi += 1
        g = SliceGroup(spec=SliceGroupSpec(
            min_member=1, queue=qname,
            priority_class=self.rng.choice(["", "hi", "lo"]),
            slice=TPUSliceSpec(
                accelerator=f"v5e-{self.rng.choice(sizes)}")))
        g.metadata.name = name
        self.store.create(store_mod.SLICEGROUPS, g)
        return name

    def chips_of(self, g: SliceGroup) -> int:
        return int(g.spec.slice.accelerator.split("-")[1])

    def groups(self) -> List[SliceGroup]:
        return self.store.list(store_mod.SLICEGROUPS)

    def admitted_by_cohort(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for g in self.groups():
            if g.status.phase not in (PHASE_INQUEUE, PHASE_RUNNING):
                continue
            q = self.queues.get(g.spec.queue)
            if q is None:
                continue
            out[q[0]] = out.get(q[0], 0) + self.chips_of(g)
        return out

    def check_cohort_capacity(self) -> Optional[str]:
        for cohort, used in self.admitted_by_cohort().items():
            cap = self.cohort_nominal[cohort]
            if used > cap:
                return (f"cohort {cohort} over capacity: {used} admitted "
                        f"chips > {cap} aggregate nominal")
        return None

    def complete_random_admitted(self) -> bool:
        admitted = [g for g in self.groups()
                    if g.status.phase in (PHASE_INQUEUE, PHASE_RUNNING)]
        if not admitted:
            return False
        victim = self.rng.choice(admitted)
        self.store.delete(store_mod.SLICEGROUPS,
                          victim.metadata.namespace, victim.metadata.name)
        self.sched.readmit()
        return True


def run_round(seed: int, steps: int = 30, verbose: bool = False) -> List[str]:
    rng = random.Random(seed)
    topo = Topology(rng)
    errors: List[str] = []
    qnames = list(topo.queues)
    for step in range(steps):
        action = rng.random()
        if action < 0.6:
            topo.add_group(rng.choice(qnames))
        elif action < 0.9:
            topo.complete_random_admitted()
        topo.sched.readmit()
        err = topo.check_cohort_capacity()
        if err:
            errors.append(f"step {step}: {err}")
            return errors
    # Starvation check: with completions freeing capacity, every
    # remaining group must admit within a bounded number of drain
    # rounds (every group was generated admissible).
    remaining = sum(1 for g in topo.groups()
                    if g.status.phase == PHASE_PENDING)
    bound = len(topo.groups()) + 5
    for round_i in range(bound):
        topo.sched.readmit()
        err = topo.check_cohort_capacity()
        if err:
            errors.append(f"drain round {round_i}: {err}")
            return errors
        pending = [g for g in topo.groups()
                   if g.status.phase == PHASE_PENDING]
        if not pending:
            break
        if not topo.complete_random_admitted():
            # Nothing admitted to complete, yet groups still pending:
            # the scheduler is stuck — starvation.
            errors.append(
                f"starvation: {len(pending)} group(s) pending with no "
                f"admitted work to wait on: "
                + ", ".join(f"{g.metadata.name}(queue={g.spec.queue}, "
                            f"chips={topo.chips_of(g)})"
                            for g in pending[:5]))
            return errors
    else:
        pending = [g for g in topo.groups()
                   if g.status.phase == PHASE_PENDING]
        if pending:
            errors.append(
                f"starvation: {len(pending)} group(s) never admitted "
                f"after {bound} drain rounds (started with {remaining} "
                "pending)")
    if verbose and not errors:
        print(f"  seed {seed}: {topo._gi} groups, "
              f"{len(topo.queues)} queues, clean", file=sys.stderr)
    return errors


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n\n")[0])
    p.add_argument("--rounds", type=int, default=50)
    p.add_argument("--seed", type=int, default=None,
                   help="base seed (default: random; printed for repro)")
    p.add_argument("--steps", type=int, default=30,
                   help="random arrive/complete steps per round")
    p.add_argument("--verbose", action="store_true")
    args = p.parse_args(argv)
    base = args.seed if args.seed is not None else \
        random.SystemRandom().randint(0, 2**31)
    print(f"verify-quota-invariants: {args.rounds} rounds, "
          f"base seed {base}", file=sys.stderr)
    for i in range(args.rounds):
        seed = base + i
        errors = run_round(seed, steps=args.steps, verbose=args.verbose)
        if errors:
            print(f"FAIL (repro: --seed {seed} --rounds 1):",
                  file=sys.stderr)
            for e in errors:
                print(f"  {e}", file=sys.stderr)
            return 1
    print("OK: admitted chips never exceeded cohort capacity; "
          "no queue starved", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
