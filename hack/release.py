#!/usr/bin/env python3
"""Release tooling (reference: py/kubeflow/tf_operator/release.py +
build_and_push_image.py).

Builds versioned artifacts from a clean tree:
  - stamps tf_operator_tpu/version.py GIT_SHA with the current commit;
  - builds an sdist + wheel into dist/ via `python -m build` when
    available, falling back to `pip wheel`/setuptools;
  - prints the docker build command for the operator image
    (build/images/tpu_operator/Dockerfile) — the image build itself runs
    in CI where a docker daemon exists.

Usage: python hack/release.py [--version X.Y.Z] [--no-stamp]
"""

from __future__ import annotations

import argparse
import os
import re
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
VERSION_FILE = os.path.join(ROOT, "tf_operator_tpu", "version.py")
PYPROJECT = os.path.join(ROOT, "pyproject.toml")


def git_sha() -> str:
    try:
        out = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                             cwd=ROOT, capture_output=True, text=True)
        return out.stdout.strip() or "unknown"
    except OSError:
        return "unknown"


def stamp(version: str | None, sha: str) -> None:
    with open(VERSION_FILE) as f:
        src = f.read()
    src = re.sub(r'GIT_SHA = "[^"]*"', f'GIT_SHA = "{sha}"', src)
    if version:
        src = re.sub(r'__version__ = "[^"]*"',
                     f'__version__ = "{version}"', src)
    with open(VERSION_FILE, "w") as f:
        f.write(src)
    if version:  # keep wheel metadata in lockstep with version_string()
        with open(PYPROJECT) as f:
            proj = f.read()
        proj = re.sub(r'^version = "[^"]*"', f'version = "{version}"',
                      proj, flags=re.M)
        with open(PYPROJECT, "w") as f:
            f.write(proj)
    print(f"release: stamped {VERSION_FILE} (sha={sha}"
          + (f", version={version})" if version else ")"))


def build_dist() -> bool:
    env = dict(os.environ, PYTHONPATH=ROOT)
    try:
        import build  # noqa: F401
        import setuptools  # noqa: F401
        # setuptools is importable, so skip build isolation — the
        # zero-network build image cannot pip-install the backend into
        # an isolated env.
        cmd = [sys.executable, "-m", "build", "--sdist", "--wheel",
               "--no-isolation", "--outdir", "dist"]
    except ImportError:
        try:
            import build  # noqa: F401

            # No local setuptools: let build isolate (needs network).
            cmd = [sys.executable, "-m", "build", "--sdist", "--wheel",
                   "--outdir", "dist"]
        except ImportError:
            cmd = [sys.executable, "-m", "pip", "wheel", "--no-deps",
                   "--no-build-isolation", "-w", "dist", "."]
    print(f"release: {' '.join(cmd)}")
    return subprocess.run(cmd, cwd=ROOT, env=env).returncode == 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--version", default=None,
                    help="override package version (default: keep current)")
    ap.add_argument("--no-stamp", action="store_true",
                    help="skip GIT_SHA stamping")
    args = ap.parse_args()

    if not args.no_stamp:
        stamp(args.version, git_sha())
    if not build_dist():
        print("release: dist build FAILED")
        return 1
    print("release: artifacts in dist/")
    print("release: operator image: docker build -f "
          "build/images/tpu_operator/Dockerfile -t tpu-operator:"
          f"{git_sha()} .")
    return 0


if __name__ == "__main__":
    sys.exit(main())
